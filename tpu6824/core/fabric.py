"""PaxosFabric — host runtime that owns the device state and the step clock.

This replaces the reference's per-process runtime: socket listeners
(`paxos/paxos.go:524-552`), the unreliable accept loop (`:528-544`), and the
test harness's filesystem network surgery (`paxos/test_test.go:712-751`
partitions, `:194-195` deafness) all become host-owned mask/probability arrays
fed into the jitted `paxos_step` kernel.  One fabric hosts G independent Paxos
groups × I instance slots × P peers and advances them all in lockstep.

Host↔device contract (designed to avoid per-op round-trips — SURVEY §7 "Host↔
device chatter"):
  - API calls (`start/status/done/...`) only touch host mirrors and pending-op
    queues under a lock; they never talk to the device.
  - A single clock thread drains queues into `apply_starts`, runs the step
    kernel, and refreshes the mirrors — one device round-trip per DISPATCH
    for the whole universe of cells, regardless of op rate.  A dispatch is
    `steps_per_dispatch` fused kernel micro-steps (lax.scan on the compact
    path), and the free-running clock double-buffers dispatches
    (`pipeline_depth`): queued ops are staged for dispatch N+1 and dispatch
    N-1's compact summary is folded into the mirrors while dispatch N runs
    on-device, with the heavy stage/apply work outside the fabric lock.
"""

from __future__ import annotations

import functools
import heapq
import os
import threading
import time
from collections import deque

import numpy as np

import jax
import jax.numpy as jnp

from tpu6824.core.intern import Intern
from tpu6824.core.jitshape import pad_i32 as _jitshape_pad_i32
from tpu6824.core.kernel import (
    NO_VAL, NPROTO, PROTO_ENABLED, PROTO_FIELDS, apply_starts,
    apply_starts_compact, init_state,
)
from tpu6824.obs import collector as obs_collector
from tpu6824.obs import metrics as obs_metrics
from tpu6824.obs import pulse as obs_pulse
from tpu6824.obs import tracing as obs_tracing
from tpu6824.utils import crashsink, durafs
from tpu6824.utils.locks import new_rlock
from tpu6824.utils.profiling import PhaseProfiler
from tpu6824.utils.trace import EventLog, dprintf

# tpuscope metrics (module scope per the metric-unregistered rule):
# fabric health gauges refreshed at every stats() poll, plus the
# columnar feed-batch histogram (one observe per retire's fan-out — the
# feed path's batch-granular registry update).  The `fabric.health.`
# prefix keeps the gauges clear of the EventLog mirror's `fabric.<name>`
# counters (the registry rejects name/kind collisions loudly).
_M_DECIDED = obs_metrics.gauge("fabric.health.decided_cells")
_M_FEED_DEPTH = obs_metrics.gauge("fabric.health.feed_depth_max")
_M_STALLED = obs_metrics.gauge("fabric.health.stalled_groups")
_M_FEED_BATCH = obs_metrics.histogram("fabric.feed_batch_cells")
# kernelscope protocol gauges: process-wide totals of the device-resident
# per-group counters, refreshed at every retire fold (monotone — gauges
# so the registry mirrors the mirror, not a second count).  One metric
# object per PROTO_FIELD, created at module scope per the
# metric-unregistered rule; the comprehension runs at import, not on the
# hot path.
_M_PROTO = {f: obs_metrics.gauge(f"fabric.protocol.{f}")
            for f in PROTO_FIELDS}
# durafault recovery gauge (its siblings — snapshot age/bytes/seq and the
# truncated horizon — live with their writer in core/checkpointd.py):
# wall seconds the last PaxosFabric.restore spent, file-read to serving.
_M_RECOVERY_TIME = obs_metrics.gauge("fabric.recovery.recovery_time_s")

# Reference unreliable-network rates: 10% of requests dropped before
# processing, a further ~20% processed but the reply discarded
# (paxos/paxos.go:528-544).
UNRELIABLE_REQ_DROP = 0.10
UNRELIABLE_REP_DROP = 0.20

# How many per-step PRNG subkeys to pre-split at once (see _next_key_locked).
_KEY_BATCH = 256

# Compact-IO defaults (all overridable per fabric / via env):
#   - auto threshold: fabrics with at least this many (g, i, p) cells use
#     the compact step path (O(active) readback) instead of the full-mirror
#     refresh;
#   - summary K: capacity of the per-step newly-decided compaction buffer
#     (overflow falls back to one full decided fetch for that step);
#   - inject bucket: fixed pad size for the scatter-based op injection
#     (fixed so jit compiles O(1) variants, not one per batch size).
_COMPACT_CELLS = int(os.environ.get("TPU6824_COMPACT_CELLS", 1 << 20))
# Loud API-boundary bound on instance seqs (done_many has the same guard):
# compact io keeps the slot→seq map on device as i32, and failing at
# Start() keeps a violation out of the step path, where it would strand
# queued ops and kill the clock thread.
_SEQ_LIMIT = 2 ** 31
_SUMMARY_K = int(os.environ.get("TPU6824_SUMMARY_K", 16384))
_INJECT_BUCKET = int(os.environ.get("TPU6824_INJECT_BUCKET", 8192))
_SMALL_BUCKET = 256  # second, tiny pad size so idle steps ship ~3KB not ~100KB
# Idle-adaptive clock: sleep this long after a step that injected nothing,
# delivered no messages, and decided nothing (0 disables; see _clock_loop).
_IDLE_SLEEP = float(os.environ.get("TPU6824_IDLE_SLEEP", 0.002))
# Pipelined multi-step clock (the host↔device amortization knobs; both
# also plumb through tpu6824.config.FabricConfig):
#   - steps per dispatch: K kernel micro-steps fused per device dispatch
#     (lax.scan around the round), so the summary readback fires once per
#     K steps instead of once per step;
#   - pipeline depth: how many dispatches the clock thread keeps in
#     flight before retiring the oldest (2 = classic double buffering:
#     stage/launch N+1 while N computes, apply N-1's mirror delta after).
#     Depth only shapes the free-running clock / step_async(); direct
#     step() calls stay synchronous (launch + retire) for deterministic
#     tests.
_STEPS_PER_DISPATCH = int(
    os.environ.get("TPU6824_CLOCK_STEPS_PER_DISPATCH", 1))
_PIPELINE_DEPTH = int(os.environ.get("TPU6824_PIPELINE_DEPTH", 2))
# Health reporting (stats()["health"]): a group counts as STALLED when it
# has live undecided instances older than this AND has decided nothing
# for this long — the signature of a group with no reachable majority
# (minority partition, too many peers dead).  Threshold only shapes the
# report, never behavior.
_STALL_AFTER = float(os.environ.get("TPU6824_STALL_AFTER", 1.0))
# Fabric-lock hold budget, enforced by the lockwatch sanitizer
# (TPU6824_SANITIZE=1 / the `sanitize` pytest fixture): the TUNING
# round-7 regression — a per-cell Python fan-out loop under this lock —
# cost ~160ms/retire and halved clerk throughput; anything approaching
# that now FAILS a sanitized run instead of shipping as a perf note.
_LOCK_BUDGET = float(os.environ.get("TPU6824_LOCK_BUDGET_FABRIC", 0.25))


@functools.partial(jax.jit, donate_argnums=(0, 1))
def _apply_compact_jit(state, slot_seq, reset_rows, cells, vids, seqs):
    """Standalone injection round for batches that overflow one bucket:
    the common case fuses injection into the step jit instead (see
    PaxosFabric._compact_fn)."""
    return apply_starts_compact(state, slot_seq, reset_rows, cells, vids,
                                seqs)


# Immediate-value tagging: small non-negative ints ride the device arrays
# AS their value id (tagged with bit 30) — no intern store round-trip, no
# refcount, nothing to GC.  The moral analog of tagged immediates in a
# runtime: the device only ever agrees on int32 ids either way (values
# never touch the TPU, kernel.py:33-34); for int payloads the id can BE
# the payload.  Interned ids grow from 0 and are bounded by the live
# window (G·I values at most), so the spaces cannot collide.
IMM_BASE = 1 << 30


class CorruptCheckpointError(RuntimeError):
    """A checkpoint file failed its checksum/length frame — torn write,
    truncation, or bit rot.  Restoring it would serve garbage as decided
    state; recovery must discard it and fall back to an older snapshot
    (core/checkpointd.py::recover_newest does exactly that)."""


# Checkpoint file frame: magic + crc32 + payload length, then the pickle
# payload.  The frame is what lets recovery tell "newest valid snapshot"
# from "the snapshot the process died in the middle of writing" — a torn
# file fails the length or the crc, never loads.
_CKPT_MAGIC = b"TPU6824K"
_CKPT_HDR = "!8sIQ"  # magic, crc32(payload), len(payload)


def frame_checkpoint(payload: bytes) -> bytes:
    import struct
    import zlib

    return struct.pack(_CKPT_HDR, _CKPT_MAGIC,
                       zlib.crc32(payload) & 0xFFFFFFFF,
                       len(payload)) + payload


def unframe_checkpoint(buf: bytes, path: str = "<buf>") -> bytes:
    """Verified payload of a framed checkpoint; raw pre-frame files pass
    through unchanged (they carry no integrity evidence — the legacy
    trade-off, kept so old checkpoints keep restoring)."""
    import struct
    import zlib

    hdr = struct.calcsize(_CKPT_HDR)
    if len(buf) < hdr or not buf.startswith(_CKPT_MAGIC):
        return buf  # pre-frame raw pickle
    _, crc, n = struct.unpack(_CKPT_HDR, buf[:hdr])
    payload = buf[hdr:]
    if len(payload) != n:
        raise CorruptCheckpointError(
            f"{path}: truncated checkpoint ({len(payload)} of {n} bytes)")
    if zlib.crc32(payload) & 0xFFFFFFFF != crc:
        raise CorruptCheckpointError(f"{path}: checkpoint crc mismatch")
    return payload


class WindowFullError(RuntimeError):
    """No free instance slot: callers are outrunning Done()/Min() GC.

    The reference has no such limit because it leaks memory instead
    (`paxos/paxos.go` keeps every un-GC'd instance in a map); the fixed
    window is what makes the device arrays bounded (SURVEY §5 long-context
    note).

    `index` is set when raised from `start_many`: ops[:index] were fully
    applied, ops[index:] were not.  Resuming from `index` once GC frees a
    slot is the precise retry; re-submitting from 0 is also SAFE (Start is
    idempotent for an undecided seq) but re-queues the prefix — duplicate
    pending entries and intern refs that live until GC."""

    def __init__(self, msg: str, index: int | None = None):
        super().__init__(msg)
        self.index = index


class DecidedSub:
    """One (group, peer) subscription to the fabric's decided-delta feed.

    The fabric pushes `(seq, value)` pairs — value already DECODED, once
    per (group, seq) across all of the group's subscribers — as cells
    transition undecided → decided in the host mirror.  Replaces the
    per-replica `drain_decided` re-scan: P replicas of a group used to
    each run the vectorized mirror pass per driver tick (3× duplicate
    scan per group); with the feed the fabric computes the delta once at
    retire and fans it out.

    Deliveries are unordered across seqs (Paxos instances decide
    independently); consumers reassemble the contiguous run they apply
    (`services/common.py::DecidedTap`).  `pop()` is lock-free on the
    consumer side (deque append/popleft are atomic); `wake` (if given) is
    called after each delivery batch — hook it to the consumer's wakeup
    event so the apply loop never polls."""

    __slots__ = ("g", "p", "wake", "_q", "_fabric", "delivered", "consumed")

    def __init__(self, fabric, g: int, p: int, wake=None):
        self.g, self.p, self.wake = g, p, wake
        self._q: deque = deque()
        self._fabric = fabric
        self.delivered = 0  # lifetime count (tests/stats)
        self.consumed = 0   # consumer-side twin: depth = delivered - consumed

    def pop(self) -> list:
        """Drain everything delivered so far: list of (seq, value).
        Deliveries arrive as per-retire BATCHES (one queue entry per
        retire, columnar (seqs, values) lists) — flattened here, so the
        fabric's fan-out never builds per-cell tuples under its lock."""
        q = self._q
        out = []
        while q:  # single consumer per sub; producers only append
            seqs, vals = q.popleft()
            out.extend(zip(seqs, vals))
        self.consumed += len(out)  # unlocked: health reads tolerate skew
        return out

    def depth(self) -> int:
        """Undrained item count — a consumer falling behind the fan-out
        shows up here (stats()["health"]["feed_depth"]).  Racy by design
        (producer and consumer bump different counters); never negative
        is all the health report needs."""
        return max(0, self.delivered - self.consumed)

    def close(self) -> None:
        self._fabric.unsubscribe_decided(self)


class PaxosFabric:
    def __init__(
        self,
        ngroups: int = 1,
        npeers: int = 3,
        ninstances: int = 64,
        seed: int = 0,
        auto_step: bool = False,
        step_sleep: float = 0.0,
        kernel: str | None = None,
        unreliable_req_drop: float = UNRELIABLE_REQ_DROP,
        unreliable_rep_drop: float = UNRELIABLE_REP_DROP,
        io_mode: str | None = None,
        summary_k: int | None = None,
        mesh=None,
        steps_per_dispatch: int | None = None,
        pipeline_depth: int | None = None,
    ):
        from tpu6824.core.kernel import paxos_step_reliable
        from tpu6824.core.pallas_kernel import get_step, resolve_impl

        self._kernel_req = kernel  # as requested (checkpoint/restore)
        self._req_drop = unreliable_req_drop
        self._rep_drop = unreliable_rep_drop
        self.G, self.I, self.P = ngroups, ninstances, npeers
        self.G_live = ngroups  # pre-padding group count (mesh fabrics pad)
        self._mesh = mesh
        self._plane = None
        if mesh is not None:
            # Device plane FIRST: it owns the shape policy — the group
            # universe is ladder-padded to a per-shard jitshape rung so
            # any service topology rides any mesh with a finite compiled
            # signature set — and every host array below sizes against
            # the padded count.  Padding groups are idle lanes: never
            # started, never fed, invisible to services.
            from tpu6824.core.fabdev import DevicePlane

            self._plane = DevicePlane(mesh, ngroups, ninstances, npeers,
                                      kernel=kernel)
            self.G = self._plane.G
        G, I, P = self.G, self.I, self.P
        self._state = init_state(G, I, P)
        if mesh is None:
            self._step_fn = get_step(kernel)
            # On the XLA path, steps with no unreliable server skip
            # Bernoulli mask generation entirely (paxos_step_reliable —
            # bit-identical at drop=0, works under partitioned links).
            # The Pallas path keeps its own mask handling (packed
            # bitplanes / maskless lane fast path).
            self._reliable_ok = resolve_impl(kernel) == "xla"
            self._step_reliable = paxos_step_reliable
            self._apply_starts = apply_starts
            if self._reliable_ok:
                # Fused K-round scan for the full-io path (one dispatch +
                # one readback per K micro-steps); the pallas/mesh engines
                # chain async dispatches instead (see _step_once_full).
                from tpu6824.core.kernel import (
                    paxos_multi_step, paxos_multi_step_reliable,
                )

                self._multi_step = paxos_multi_step
                self._multi_reliable = paxos_multi_step_reliable
            else:
                self._multi_step = self._multi_reliable = None
        else:
            # Mesh-hosted fabric (SURVEY §0's architecture sentence): the
            # (G, I, P) consensus universe lives sharded over the device
            # mesh — peer-axis reductions become psum over ICI when 'p'
            # spans devices — while the host API is unchanged (mirrors are
            # gathered by the per-step readback; compact io keeps that
            # readback O(active cells)).  All placement decisions live in
            # the device plane (core/fabdev.py); the fabric consumes its
            # compiled entry points and shardings.
            plane = self._plane
            self._state = plane.place_state(self._state)
            self._step_fn = plane.step_fn
            self._multi_step = self._multi_reliable = None
            self._reliable_ok = plane.reliable_ok
            self._step_reliable = plane.step_reliable
            self._apply_starts = plane.apply_starts
            self._sh_link, self._sh_done = plane.sh_link, plane.sh_done
            self._sh_key, self._sh_drop = plane.sh_key, plane.sh_drop
        self._key = jax.random.key(seed)
        self._key_arr = None  # current split batch; indexed by countdown
        self._key_buf_n = 0
        # Trace-warm the EXACT refill expressions OUTSIDE any lock: the
        # first unreliable dispatch otherwise pays the jit traces inside
        # _drain_and_stage_locked — a one-time fabric-lock hold the
        # lockwatch budget rightly rejects.  The avals must match what
        # _next_key_locked runs (split → keys[0] gather on (B+1,) →
        # keys[1:] slice → gather on (B,)); jit caches by shape, so this
        # costs once per process, not per fabric.
        _warm = jax.random.split(self._key, _KEY_BATCH + 1)
        _warm[0], _warm[1:][_KEY_BATCH - 1]

        # IO mode (VERDICT r4 weak #2 — the full-mirror readback wall):
        #   "full"    — device_get the whole decided/touched mirror per step
        #               (simple; O(G·I·P) PCIe traffic per step);
        #   "compact" — device-side newly-decided compaction + (G, P)
        #               Max() reduction; readback is O(active cells);
        #   "auto"    — compact iff the cell universe is large enough for
        #               the mirror copy to dominate a step.
        # Both modes maintain identical host mirrors (m_decided is exact
        # either way — decided is sticky per tenancy, so the incremental
        # scatter equals the full refresh); every API reads the mirrors.
        io_mode = io_mode or os.environ.get("TPU6824_IO_MODE", "auto")
        if io_mode == "auto":
            io_mode = "compact" if G * I * P >= _COMPACT_CELLS else "full"
        if io_mode not in ("full", "compact"):
            raise ValueError(f"unknown io_mode {io_mode!r}")
        self._io_mode = io_mode
        self._summary_k = min(G * I * P, summary_k or _SUMMARY_K)
        self._slot_seq_dev = None
        if io_mode == "compact":
            self._slot_seq_dev = jnp.full((G, I), -1, jnp.int32)
            if mesh is not None:
                self._slot_seq_dev = self._plane.place_slot_seq(
                    self._slot_seq_dev)
        self._compact_fns: dict = {}
        self._zero_drop = None  # lazily-built (G, P, P) f32 zeros
        self._dummy_keys = None  # stacked (K,) dummies for the fused scan

        # Pipelined multi-step clock state (see the knob comment above):
        self._spd = max(1, int(steps_per_dispatch
                               if steps_per_dispatch is not None
                               else _STEPS_PER_DISPATCH))
        self._pipeline_depth = max(1, int(pipeline_depth
                                          if pipeline_depth is not None
                                          else _PIPELINE_DEPTH))
        self._inflight: deque = deque()  # launched, unretired dispatches
        # Summary-overflow resync epoch: a full-mirror resync at retire
        # reads the NEWEST device state, which includes dispatches still
        # in flight — those must recount absolutely at their own retire
        # instead of adding their (already-mirrored) increments again.
        self._resync_epoch = 0

        # Host-owned network condition (device inputs):
        self._link = np.ones((G, P, P), bool)
        self._link_dev = None  # device copy; None = stale (net changed)
        self._unreliable = np.zeros((G, P), bool)  # per receiving server
        self._done = np.full((G, P), -1, np.int32)
        # Lock-free Done staging (done_deferred): RSM drivers write their
        # watermark here WITHOUT the fabric lock; the clock folds it into
        # _done/m_done_view/_peer_min under its own lock at the next
        # staging.  One writer per (g, p) cell (that replica's driver),
        # GIL-atomic numpy scalar stores, max-monotone — so the fold can
        # never regress a watermark.
        self._done_async = np.full((G, P), -1, np.int32)
        self._pmin_i32 = np.empty((G, P), np.int32)  # scratch for min-reduce

        # Host mirrors of device outputs (device dtype — int32 — so the
        # per-step refresh is a straight copy, no astype pass):
        self.m_decided = np.full((G, I, P), NO_VAL, np.int32)
        self.m_done_view = np.full((G, P, P), -1, np.int32)
        # Min() cache: _peer_min[g, p] = 1 + min_q done_view[g, p, q],
        # refreshed vectorized once per step and on done() — so the hot API
        # calls (start/status, O(ops/sec) of them) read a scalar instead of
        # reducing a row each (the O(G) bookkeeping wall, VERDICT r3 weak #2).
        self._peer_min = np.zeros((G, P), np.int64)
        self._max_seq = np.full((G, P), -1, np.int64)  # Max() running high-water
        # Observability (SURVEY §5 build note): per-step event log + counters.
        # The EventLog counters are the single source of truth for steps/msgs;
        # steps_total/msgs_total below are read-through views.  The
        # registry prefix mirrors every bump into the process-global
        # tpuscope metrics registry (obs/metrics.py).
        self.events = EventLog(registry_prefix="fabric")
        self._decided_cells = 0  # running count of decided (g, i, p) cells
        # kernelscope: host mirror of the device-resident per-group
        # protocol counters (PROTO_FIELDS columns), folded from the
        # once-per-dispatch summary readback — plus two time-bucketed
        # windows of recent events (rolled on the FOLD side, i.e. by the
        # clock thread) so stall diagnosis reasons over what happened
        # recently (is this group failing quorums NOW?) without stats()
        # mutating anything: concurrent observers (health polls, the
        # fleet collector, the fabric_service RPC) all see the same
        # window and cannot consume each other's diagnosis.
        self._proto = np.zeros((G, NPROTO), np.int64)
        self._proto_version = 0  # bumped per fold (per_group cache key)
        self._proto_window = float(
            os.environ.get("TPU6824_PROTO_WINDOW", "0.5"))
        self._proto_bucket_cur = np.zeros((G, NPROTO), np.int64)
        self._proto_bucket_prev = np.zeros((G, NPROTO), np.int64)
        self._proto_bucket_t = time.monotonic()
        self._protocol_cache: tuple[int, dict] | None = None
        # Health bookkeeping (stats()["health"]): when the last dispatch
        # retired into the mirrors, when each group last decided anything,
        # and when each live slot was allocated — enough to report a
        # stalled (majority-less) group instead of letting it hang
        # silently (see _health_locked).
        now = time.monotonic()
        self._last_retire_t = now
        self._g_last_decided = np.full(G, now, np.float64)
        self._slot_alloc_t = np.zeros((G, I), np.float64)

        # Slot management (host only): which absolute seq lives in each slot.
        self._slot_seq = np.full((G, I), -1, np.int64)
        self._seq2slot: list[dict[int, int]] = [dict() for _ in range(G)]
        # Per-group free-slot MIN-HEAP (invariant: slot is listed iff
        # _slot_seq[g, slot] == -1).  Smallest-slot-first makes allocation
        # a pure function of the free SET, not of GC batch boundaries —
        # required for the K-step parity contract: the K=1 clock may GC a
        # window across several retires where the fused K-step clock GCs
        # it in one, and a LIFO freelist would then hand out different
        # slots.  A freed slot may carry a pending reset; that is safe to
        # hand out because apply_starts applies resets before starts
        # within the same step.
        self._free: list[list[int]] = [list(range(I)) for _ in range(G)]
        self._live_slots = 0  # allocated - GC'd (idle-clock predicate)
        self._slot_vids: list[list[list[int]]] = [
            [[] for _ in range(I)] for _ in range(G)
        ]  # interned ids referenced by each slot (for GC decref)

        self.intern = Intern()

        # Decided-delta feed (the service-stack half of the pipelined
        # clock): per-(g, p) subscriber lists, the set of groups with any
        # subscriber (fan-out skip predicate — zero overhead for
        # bench/kernel fabrics with no services attached), and the
        # per-group decode-once cache: seq → decoded payload, filled on
        # the FIRST newly-decided cell of a (g, seq) and evicted by the
        # window GC — so P replicas consuming the feed cost one intern
        # decode per decided instance, not one per replica.
        self._subs: dict[tuple[int, int], list[DecidedSub]] = {}
        self._sub_groups: set[int] = set()
        self._feed_vals: list[dict[int, object]] = [dict() for _ in range(G)]
        # Host-side phase profiler (stage → dispatch → retire → feed;
        # services add apply/notify through the same object via
        # PaxosPeer.profiler) — surfaced in stats()["phases"].
        self.profiler = PhaseProfiler()

        self._lock = new_rlock("PaxosFabric._lock", hold_budget_s=_LOCK_BUDGET)
        self._pending_starts: list[tuple[int, int, int, int, int]] = []  # (g, slot, p, vid, seq)
        self._pending_resets: list[tuple[int, int]] = []  # (g, slot)
        self._dead = np.zeros((G, P), bool)
        # Durability/recovery status (stats()["health"]["recovery"]):
        # merged via set_recovery_info by restore() and the continuous
        # checkpointer.  Empty = this fabric neither restored from a
        # snapshot nor has a checkpoint daemon attached.
        self._recovery: dict = {}

        self._running = False
        self._last_step_active = True  # idle-adaptive clock (see _clock_loop)
        self._clock_wake = threading.Event()
        # Start/stop transition mutex (RLock: resume_clock restarts the
        # clock while holding it) + the stop-intent counter backing the
        # pause/resume arbitration (see pause_clock).
        self._clock_mu = threading.RLock()
        self._clock_stop_intents = 0
        self._thread: threading.Thread | None = None
        self._step_sleep = step_sleep
        self._stepped = threading.Condition(self._lock)
        if auto_step:
            self.start_clock()

    # ------------------------------------------------------------------ clock

    def start_clock(self):
        # _clock_mu serializes start/stop TRANSITIONS (never held by the
        # clock thread itself): without it, a stop_clock racing another
        # caller's start_clock could observe _thread created but not yet
        # started and join() it (RuntimeError) — the continuous
        # checkpointer cycles the clock around every snapshot, so
        # concurrent stop/start is now an ordinary interleaving, not a
        # harness bug.
        with self._clock_mu:
            with self._lock:
                if self._running:
                    return
                self._running = True
            self._thread = threading.Thread(
                target=crashsink.guarded(self._clock_loop, "fabric-clock"),
                daemon=True)
            self._thread.start()

    def stop_clock(self):
        with self._clock_mu:
            # An explicit stop VOTE: any pause_clock holder's deferred
            # resume_clock observes the bump and leaves the clock
            # stopped — the stop_clock caller now owns that state.
            self._clock_stop_intents += 1
            with self._lock:
                self._running = False
            if self._thread:
                self._thread.join()
                self._thread = None

    def pause_clock(self) -> tuple[bool, int]:
        """Borrow-the-clock arbitration (the continuous checkpointer's
        snapshot pause): atomically stop the clock and return
        (was_running, token) for a later `resume_clock(was, token)`.
        Unlike stop_clock, a pause casts no stop vote — but the resume
        is SKIPPED if anyone called stop_clock in between, so a
        harness/test that stops the clock mid-snapshot is never undone
        by the daemon's restart."""
        with self._clock_mu:
            with self._lock:
                was = self._running
                self._running = False
            if self._thread:
                self._thread.join()
                self._thread = None
            return was, self._clock_stop_intents

    def resume_clock(self, was_running: bool, token: int) -> bool:
        """Second half of pause_clock: restart only if the clock was
        running at pause time AND no stop_clock intervened."""
        with self._clock_mu:
            if not was_running or self._clock_stop_intents != token:
                return False
            self.start_clock()  # RLock: safe to re-enter _clock_mu
            return True

    def _clock_loop(self):
        # Idle-adaptive pacing: a step that injected nothing, delivered no
        # remote messages, and decided nothing is pure bookkeeping — on a
        # busy host the free-running clock would spend a whole core
        # re-running it.  Sleep briefly after such steps (still ~500
        # steps/s, plenty for done-gossip convergence) and snap back to
        # full speed the moment anything happens.
        while True:
            with self._lock:
                if not self._running:
                    # Retire whatever the pipelined loop left in flight so
                    # stop_clock() hands back fully-applied mirrors.
                    break
            self.step_async()
            if self._step_sleep:
                time.sleep(self._step_sleep)
            elif _IDLE_SLEEP and not self._last_step_active:
                # Interruptible: any queued op wakes the clock instantly
                # (and a step always follows the wait, so clearing cannot
                # strand a queued op), so idling never adds op latency.
                self._clock_wake.wait(_IDLE_SLEEP)
                self._clock_wake.clear()
        self.flush()

    def step(self, n: int = 1):
        """Advance the whole fabric by n dispatches of `steps_per_dispatch`
        kernel micro-steps each, synchronously (callable from the clock
        thread or directly in deterministic tests).  Any dispatches left in
        flight by step_async() are retired first."""
        self.flush()
        for _ in range(n):
            self._step_once()

    def step_async(self):
        """Pipelined advance: launch one dispatch, then retire the oldest
        in-flight dispatches down to `pipeline_depth - 1` — so with depth 2
        the host stages/applies mirrors for dispatch N±1 while dispatch N
        computes on-device.  API calls remain safe concurrently (they only
        touch host mirrors under the lock).  Falls back to a synchronous
        step on the full-io path, which has no launch/retire split — but
        first retires anything a DEEPER previous depth left in flight
        (set_pipeline_depth(1) mid-pipeline must not strand a launched
        dispatch: later dispatches never re-report its newly-decided
        summary, so an unretired entry would hold those decisions out of
        the mirrors until the clock stopped)."""
        if self._io_mode != "compact" or self._pipeline_depth <= 1:
            self.flush()
            self._step_once()
            return
        self._inflight.append(self._launch_compact())
        while len(self._inflight) >= self._pipeline_depth:
            self._retire_compact(self._inflight.popleft())

    def flush(self):
        """Retire every in-flight dispatch (no-op when none are)."""
        while self._inflight:
            self._retire_compact(self._inflight.popleft())

    def _next_key_locked(self):
        # Amortized PRNG: one split call per _KEY_BATCH steps instead of one
        # per step (jax.random.split is a host round-trip).  The batch is
        # kept AS the device array with a countdown cursor: the original
        # `list(keys[1:])` materialized 256 key scalars in one go and cost
        # >1s under the fabric lock at every refill — the first hold-budget
        # violation lockwatch ever caught (tpusan PR).  Indexing hands out
        # the same keys in the same order (tail first), one cheap gather
        # per step.
        if not self._key_buf_n:
            keys = jax.random.split(self._key, _KEY_BATCH + 1)
            self._key = keys[0]
            self._key_arr = keys[1:]
            self._key_buf_n = _KEY_BATCH
        self._key_buf_n -= 1
        sub = self._key_arr[self._key_buf_n]
        if self._plane is not None:
            sub = self._plane.put_key(sub)
        return sub

    def _put(self, kind: str, x):
        """Host array → device, honoring the mesh placement when the
        fabric is mesh-hosted (a committed single-device array would
        conflict with the sharded step's in_shardings)."""
        if self._plane is None:
            return jnp.asarray(x)
        return self._plane.put(kind, x)

    def _step_once(self):
        if self._io_mode == "compact":
            self._step_once_compact()
        else:
            self._step_once_full()

    def _drain_and_stage_locked(self):
        """The under-lock staging shared by both step paths: swap out the
        op queues — dropping starts whose slot was GC-recycled while they
        were queued (the slot no longer maps to their seq: arming the
        freed slot would run a ghost round with a value id whose intern
        ref the GC already dropped; the vectorized form of
        `_start_is_live`) — and stage the network condition for the
        kernel.  Returns (s_arr, r_arr, link, done, reliable, keys,
        drop_req, drop_rep); `keys` is a list of `steps_per_dispatch`
        per-micro-step PRNG subkeys, popped in the same order a K=1 clock
        would pop them (the multi-step parity contract); the drop/key
        slots are None on the reliable fast path.  Only the queue swap
        and network snapshot need the lock — callers do the heavy pad/
        dedup work outside it so API threads keep running while a
        dispatch is being staged."""
        self._fold_done_async_locked()
        starts = self._pending_starts
        resets = self._pending_resets
        self._pending_starts = []
        self._pending_resets = []
        s_arr = r_arr = None
        if starts:
            s_arr = np.asarray(starts, dtype=np.int64)  # (N, 5): g, slot, p, vid, seq
            keep = (self._slot_seq[s_arr[:, 0], s_arr[:, 1]]
                    == s_arr[:, 4])
            s_arr = s_arr[keep] if not keep.all() else s_arr
        if resets:
            r_arr = np.asarray(resets, dtype=np.int64)  # (N, 2)
        if self._link_dev is None:
            self._link_dev = self._put("link", self._link)
        link = self._link_dev
        done = self._put("done", self._done)
        reliable = self._reliable_ok and not bool(self._unreliable.any())
        keys = drop_req = drop_rep = None
        if not reliable:
            # Per-edge drop probabilities from per-server unreliable
            # flags: the *destination* server's accept loop drops.
            unrel = self._unreliable.astype(np.float32)  # (G, P)
            e = np.broadcast_to(
                unrel[:, None, :], (self.G, self.P, self.P))
            drop_req = self._put("drop", e * self._req_drop)
            drop_rep = self._put("drop", e * self._rep_drop)
            keys = [self._next_key_locked() for _ in range(self._spd)]
        return s_arr, r_arr, link, done, reliable, keys, drop_req, drop_rep

    def _step_once_full(self):
        t0 = time.perf_counter_ns()
        t0_mono = time.monotonic_ns()
        with self._lock:
            (s_arr, r_arr, link, done, reliable, keys, drop_req,
             drop_rep) = self._drain_and_stage_locked()

        state = self._state
        if s_arr is not None or r_arr is not None:
            reset = np.zeros((self.G, self.I), bool)
            sa = np.zeros((self.G, self.I, self.P), bool)
            sv = np.full((self.G, self.I, self.P), NO_VAL, np.int32)
            if r_arr is not None:
                reset[r_arr[:, 0], r_arr[:, 1]] = True
            if s_arr is not None and len(s_arr):
                sa[s_arr[:, 0], s_arr[:, 1], s_arr[:, 2]] = True
                sv[s_arr[:, 0], s_arr[:, 1], s_arr[:, 2]] = s_arr[:, 3]
            state = self._apply_starts(
                state, jnp.asarray(reset), jnp.asarray(sa), jnp.asarray(sv)
            )
        self.profiler.add("stage", time.perf_counter_ns() - t0)
        t0 = time.perf_counter_ns()

        # K micro-steps, ONE device_get.  The XLA engine fuses the rounds
        # into a single scan dispatch (kernel.paxos_multi_step*); the
        # mesh/pallas engines chain K async dispatches instead, with
        # touched/msgs merged on-device — either way the host round-trip
        # cost is paid once per dispatch, not once per micro-step.
        if self._spd > 1 and self._multi_step is not None:
            if reliable:
                state, io = self._multi_reliable(state, link, done,
                                                 self._spd)
            else:
                state, io = self._multi_step(state, link, done,
                                             self._stacked_keys(keys),
                                             drop_req, drop_rep)
            touched_acc, msgs_acc = io.touched, io.msgs
            proto_acc = io.proto  # scan already merged the dispatch total
        else:
            touched_acc = msgs_acc = proto_acc = None
            for k in range(self._spd):
                if reliable:
                    state, io = self._step_reliable(state, link, done)
                else:
                    state, io = self._step_fn(state, link, done, keys[k],
                                              drop_req, drop_rep)
                touched_acc = (io.touched if touched_acc is None
                               else touched_acc | io.touched)
                msgs_acc = (io.msgs if msgs_acc is None
                            else msgs_acc + io.msgs)
                proto_acc = (io.proto if proto_acc is None
                             else proto_acc + io.proto)
        self._state = state
        self.profiler.add("dispatch", time.perf_counter_ns() - t0)
        t_r = time.perf_counter_ns()
        t_r_mono = time.monotonic_ns()
        # Protocol counters ride the SAME device_get (the zero-extra-
        # readback contract); with TPU6824_PROTO=0 they are omitted from
        # the fetch entirely.
        if PROTO_ENABLED:
            # tpusan: ok(readback-in-step) — THE sanctioned once-per-
            # dispatch summary readback (full-io path); the protocol
            # counters ride this fetch, nothing may add another
            decided, done_view, touched, msgs, proto = jax.device_get(
                (io.decided, io.done_view, touched_acc, msgs_acc,
                 proto_acc))
        else:
            proto = None
            # tpusan: ok(readback-in-step) — same sanctioned summary
            # readback, telemetry-off arm (one fewer fetched array)
            decided, done_view, touched, msgs = jax.device_get(
                (io.decided, io.done_view, touched_acc, msgs_acc)
            )

        with self._lock:
            # device_get output can be read-only; mirrors must be writable
            # (GC wipes recycled rows, the done() diagonal stays monotone).
            decided = np.array(decided)
            done_view = np.array(done_view)
            # Fresh mirror transitions (<0 → >=0): the decided-delta feed's
            # payload and the per-group health timestamp in one diff (GC
            # wipes and their device-side resets complete within one
            # synchronous step, so the diff can never resurrect a recycled
            # tenant).  Before _gc_locked, while the slot map still names
            # the fed seqs.
            trans = (decided >= 0) & (self.m_decided < 0)
            gdec = trans.any(axis=(1, 2))
            if gdec.any():
                self._g_last_decided[gdec] = time.monotonic()
            if self._sub_groups:
                flat = np.nonzero(trans.reshape(-1))[0]
                if len(flat):
                    self.profiler.add("retire",
                                      time.perf_counter_ns() - t_r, count=0)
                    self._feed_cells_locked(flat, decided.reshape(-1)[flat])
                    t_r = time.perf_counter_ns()
            self.m_decided = decided
            self.m_done_view = done_view
            # done() calls that landed while the step was in flight are in
            # self._done but not yet in the device output — keep the own-done
            # diagonal monotone so Min() never transiently regresses.
            pidx = np.arange(self.P)
            done_view[:, pidx, pidx] = np.maximum(
                done_view[:, pidx, pidx], self._done)
            np.minimum.reduce(done_view, axis=2, out=self._pmin_i32)
            self._peer_min = self._pmin_i32.astype(np.int64) + 1
            ndec = int((self.m_decided >= 0).sum())
            # _decided_cells was decremented by GC for wiped cells, so this
            # delta counts decisions landing in recycled slots too.
            newly = ndec - self._decided_cells
            self._decided_cells = ndec
            if proto is not None:
                self._fold_proto_locked(proto)
            self.events.bump("steps", self._spd)
            self.events.bump("msgs", int(msgs))
            if newly > 0:
                self.events.bump("decided_cells", newly)
                dprintf("fabric", "step %d: +%d decided cells, %d msgs",
                        self.steps_total, newly, int(msgs))
            # Max() bookkeeping: highest seq this peer has participated in.
            seqs = np.where(touched, self._slot_seq[:, :, None], -1)  # (G,I,P)
            self._max_seq = np.maximum(self._max_seq, seqs.max(axis=1))
            self._last_step_active = (
                s_arr is not None or r_arr is not None or int(msgs) > 0
                or newly > 0
                or self._live_slots * self.P > self._decided_cells)
            gc_drops = self._gc_locked()
            self._stepped.notify_all()
            self._last_retire_t = time.monotonic()
            self.profiler.add("retire", time.perf_counter_ns() - t_r)
            if (s_arr is not None or r_arr is not None or int(msgs) > 0
                    or newly > 0):
                # Flight-recorder batch spans (always-on, activity-gated
                # — cf. _retire_compact): the full-io path has no
                # launch/retire split, so stage+dispatch ride one
                # dispatch span and retire covers the readback+mirror.
                obs_tracing.batch("fabric.dispatch.batch", t0_mono,
                                  steps=self._spd,
                                  staged=0 if s_arr is None else len(s_arr))
                obs_tracing.batch("fabric.retire.batch", t_r_mono,
                                  steps=self._spd, newly=int(newly),
                                  msgs=int(msgs))
        self._decref_many(gc_drops)

    # ------------------------------------------------- compact step path

    def _compact_fn(self, reliable: bool):
        """The fused injection+multi-round+summary jit.  Injection is fused
        so the pre-dispatch `decided` (= the newly-decided diff's baseline)
        is an internal value, not an extra host round trip; the
        `steps_per_dispatch` micro-rounds run inside ONE lax.scan, so the
        whole dispatch is a single device program; and the summary is fused
        so the readback is (cnt, K idx/vals/seqs, (G,P) maxseq, done_view,
        msgs) — O(active cells), ONCE per dispatch — instead of one
        (G, I, P) mirror copy per step.  `decided` is sticky within a
        dispatch (resets only inject at dispatch start), so diffing the
        final state against the baseline is exactly the union of the
        per-step diffs.  The per-entry `seqs` readback is the tenancy tag
        the pipelined retire needs: a summary entry whose slot the host
        GC'd/reassigned after launch is recognizable (host slot→seq no
        longer matches) and dropped instead of resurrecting a recycled
        row.  This is what lets the service path ride the kernel at
        north-star shape (Status stays a local host-mirror read,
        paxos/paxos.go:434-447)."""
        fn = self._compact_fns.get(reliable)
        if fn is not None:
            return fn
        step = self._step_fn
        step_reliable = self._step_reliable
        K = self._summary_k
        G, I, P = self.G, self.I, self.P
        nrows, ncells = G * I, G * I * P

        def fused(state, slot_seq, reset_rows, cells, vids, seqs,
                  link, done, keys, drop_req, drop_rep):
            state, slot_seq = apply_starts_compact(
                state, slot_seq, reset_rows, cells, vids, seqs)
            prev = state.decided

            def body(st, key):
                if reliable:
                    st2, io = step_reliable(st, link, done)
                else:
                    st2, io = step(st, link, done, key, drop_req, drop_rep)
                return st2, (io.touched, io.msgs, io.proto)

            st2, (touched_k, msgs_k, proto_k) = jax.lax.scan(body, state,
                                                             keys)
            touched = touched_k.any(axis=0)
            msgs = msgs_k.sum().astype(jnp.int32)
            newly = (st2.decided >= 0) & (prev < 0)
            flat = newly.reshape(-1)
            cnt = flat.sum().astype(jnp.int32)
            idx = jnp.nonzero(flat, size=K, fill_value=ncells)[0]
            idx = idx.astype(jnp.int32)
            vals = st2.decided.reshape(-1)[jnp.minimum(idx, ncells - 1)]
            iseqs = slot_seq.reshape(-1)[
                jnp.minimum(idx // P, nrows - 1)]
            maxseq = jnp.max(
                jnp.where(touched, slot_seq[:, :, None], jnp.int32(-1)),
                axis=1)  # (G, P)
            out = (st2, slot_seq, cnt, idx, vals, iseqs, maxseq,
                   st2.done_view, msgs)
            if PROTO_ENABLED:
                # kernelscope: the dispatch's per-group protocol event
                # totals ride the same summary tuple — the readback grows
                # by one tiny (G, NPROTO) i32 array; with TPU6824_PROTO=0
                # the reductions above are dead code XLA eliminates.
                out += (proto_k.sum(axis=0),)
            return out

        fn = jax.jit(fused, donate_argnums=(0, 1))
        self._compact_fns[reliable] = fn
        return fn

    def _stacked_keys(self, keys):
        """One (K,) key array for the fused scan; reliable dispatches reuse
        a cached dummy stack (the scan ignores it at zero drop).  On a
        mesh-hosted fabric the stack gets the replicated key sharding —
        a committed unsharded array would conflict with the sharded
        step's in_shardings (same reason _put exists)."""
        if keys is not None:
            ks = jnp.stack(keys)
            if self._plane is not None:
                ks = self._plane.put_key(ks)
            return ks
        if self._dummy_keys is None:
            ks = jax.random.split(jax.random.key(0), self._spd)
            if self._plane is not None:
                ks = self._plane.put_key(ks)
            self._dummy_keys = ks
        return self._dummy_keys

    # Shared jit-shape discipline (core/jitshape.py): the injection path
    # and the devapply decided-path kernel (ISSUE 16) pad through ONE
    # implementation, so every host→device handoff in the tree carries
    # the same fixed-bucket signature guarantees jitguard enforces.
    _pad_i32 = staticmethod(_jitshape_pad_i32)

    def _launch_compact(self):
        """Stage the queued ops and launch ONE fused dispatch
        (`steps_per_dispatch` micro-steps); returns the pending handle for
        `_retire_compact`.  Only the queue swap + network snapshot hold
        the lock — the pad/dedup/device-put work and the dispatch itself
        run outside it, so `start_many`/`status_many` callers proceed
        concurrently with an in-flight dispatch (the double-buffering half
        of the pipelined clock)."""
        G, I, P = self.G, self.I, self.P
        nrows, ncells = G * I, G * I * P
        t0 = time.perf_counter_ns()
        with self._lock:
            (s_arr, r_arr, link, done, reliable, keys, drop_req,
             drop_rep) = self._drain_and_stage_locked()
            if reliable:
                # The fused jit takes one signature; the reliable variant
                # ignores these, so cached dummies keep the call cheap.
                if self._zero_drop is None:
                    self._zero_drop = self._put(
                        "drop", np.zeros((G, P, P), np.float32))
                drop_req = drop_rep = self._zero_drop
            epoch = self._resync_epoch
        sub = self._stacked_keys(keys)
        rrows = np.empty(0, np.int64)
        if r_arr is not None:
            rrows = r_arr[:, 0] * I + r_arr[:, 1]
        scells = svids = sseqs = None
        if s_arr is not None and len(s_arr):
            cells_all = (s_arr[:, 0] * I + s_arr[:, 1]) * P + s_arr[:, 2]
            # Dedup last-wins per cell — the dense scatter's semantics,
            # made deterministic for the device scatter.
            _, last_rev = np.unique(cells_all[::-1], return_index=True)
            sel = len(cells_all) - 1 - last_rev
            scells = cells_all[sel]
            svids = s_arr[sel, 3]
            sseqs = s_arr[sel, 4]

        # Chunked injection: resets first (a deferred reset could wipe a
        # slot's NEXT tenant), then starts; everything beyond the last
        # bucket goes through standalone injection jits.  Common case:
        # zero standalone calls, one fused call.
        B = _INJECT_BUCKET
        nr = len(rrows)
        ns = 0 if scells is None else len(scells)
        chunks = []
        ri = si = 0
        while True:
            r_take = min(B, nr - ri)
            s_take = min(B, ns - si) if ri + r_take == nr else 0
            chunks.append((ri, ri + r_take, si, si + s_take))
            ri += r_take
            si += s_take
            if ri == nr and si == ns:
                break
        state, slot_dev = self._state, self._slot_seq_dev

        def pads(c, bucket=None):
            a, b, cc, d = c
            if bucket is None:
                bucket = (_SMALL_BUCKET
                          if max(b - a, d - cc) <= _SMALL_BUCKET else B)
            return (self._pad_i32(rrows[a:b], nrows, bucket),
                    self._pad_i32(None if scells is None else scells[cc:d],
                                  ncells, bucket),
                    self._pad_i32(None if svids is None else svids[cc:d],
                                  0, bucket),
                    self._pad_i32(None if sseqs is None else sseqs[cc:d],
                                  0, bucket))

        last_pads = pads(chunks[-1])
        self.profiler.add("stage", time.perf_counter_ns() - t0)
        if nr + ns:
            # Flight-recorder batch span (always-on, activity-gated):
            # interleaves with any traced op's causal chain by timestamp.
            obs_tracing.batch("fabric.stage.batch",
                              time.monotonic_ns()
                              - (time.perf_counter_ns() - t0),
                              resets=nr, starts=ns)
        t0 = time.perf_counter_ns()
        t0_mono = time.monotonic_ns()
        for c in chunks[:-1]:
            state, slot_dev = _apply_compact_jit(state, slot_dev,
                                                 *pads(c, bucket=B))
        out = self._compact_fn(reliable)(
            state, slot_dev, *last_pads, link, done, sub,
            drop_req, drop_rep)
        self.profiler.add("dispatch", time.perf_counter_ns() - t0)
        if nr + ns:
            obs_tracing.batch("fabric.dispatch.batch", t0_mono,
                              steps=self._spd, staged=nr + ns)
        st2, slot_dev = out[0], out[1]
        self._state = st2
        self._slot_seq_dev = slot_dev
        # out[2:]: cnt, idx, vals, iseqs, maxseq, done_view, msgs — all
        # still device futures; device_get happens at retire.
        return (out[2:], nr + ns, epoch)

    def _retire_compact(self, pending):
        """Fetch one dispatch's summary and fold it into the host mirrors
        (the mirror-apply half of the pipeline; the blocking device_get
        runs outside the lock).  Newly-decided cells — fresh <0 → >=0
        mirror transitions only — are fanned out to the decided-delta
        feed before GC runs, while the slot map still names their seqs."""
        handles, n_inject, epoch = pending
        t_r = time.perf_counter_ns()
        t_r_mono = time.monotonic_ns()
        # One device_get per dispatch — the protocol counters (when
        # enabled) are the tuple's optional last element, never a second
        # fetch (the zero-extra-readback contract, asserted in
        # tests/test_kernelscope.py).
        # tpusan: ok(readback-in-step) — THE sanctioned once-per-dispatch
        # summary readback (compact-io retire fold)
        fetched = jax.device_get(handles)
        (cnt, idx, vals, iseqs, maxseq, done_view, msgs) = fetched[:7]
        proto = fetched[7] if len(fetched) > 7 else None
        G, I, P = self.G, self.I, self.P
        ncells = G * I * P

        with self._lock:
            cnt = int(cnt)
            feed_flat = feed_vids = None
            if cnt > self._summary_k:
                # Compaction overflow (a burst decided more cells than K):
                # one full fetch, mirrors resync absolutely.  The fetch
                # reads the NEWEST device state — with dispatches in
                # flight that runs ahead of this retire, so later retires
                # of already-launched dispatches must recount instead of
                # re-adding increments the resync already mirrored
                # (the epoch check below).
                # tpusan: ok(lock-blocking-call, readback-in-step) — rare
                # overflow resync: must be atomic with the mirror swap (a
                # start_many landing between fetch and mirror write would
                # see torn state), and NOT a steady-state readback
                # (summary_k is sized to the burst; the zero-extra-
                # readback test pins the per-dispatch count on the
                # non-overflow path).
                decided = np.array(jax.device_get(self._state.decided))
                if self._pending_resets:
                    # Queued GC wipes not yet injected into any launched
                    # dispatch: the fetched state still carries the old
                    # tenants; the mirror must not resurrect them.
                    r = np.asarray(self._pending_resets, dtype=np.int64)
                    decided[r[:, 0], r[:, 1], :] = NO_VAL
                # Mirror transitions this resync makes: the feed delta
                # (same rule as the scatter path, computed by diff
                # because the summary overflowed) and the per-group
                # health timestamp.
                trans = (decided >= 0) & (self.m_decided < 0)
                gdec = trans.any(axis=(1, 2))
                if gdec.any():
                    self._g_last_decided[gdec] = time.monotonic()
                if self._sub_groups:
                    feed_flat = np.nonzero(trans.reshape(-1))[0]
                    feed_vids = decided.reshape(-1)[feed_flat]
                self.m_decided = decided
                ndec = int((decided >= 0).sum())
                newly = ndec - self._decided_cells
                self._decided_cells = ndec
                self._resync_epoch += 1
            else:
                applied = 0
                if cnt:
                    valid = idx < ncells
                    pidx_v = idx[valid]
                    # Tenancy filter: with dispatches pipelined, the host
                    # may have GC'd/reassigned a slot after this dispatch
                    # launched; its summary entries then carry a seq the
                    # host slot map no longer holds — drop them (the
                    # recycled row was already wiped, and the device wipe
                    # rides the queued reset).  Synchronous clocks never
                    # trip this (the filter keeps everything).
                    live = (self._slot_seq.reshape(-1)[pidx_v // P]
                            == iseqs[valid])
                    pidx_v = pidx_v[live] if not live.all() else pidx_v
                    vals_v = vals[valid][live]
                    # A retire launched before an overflow resync may
                    # re-report cells the resync already mirrored (and
                    # fed) — the fresh-transition filter keeps the feed
                    # exactly-once per tenancy, and the health timestamp
                    # honest: only cells deciding NOW may refresh a
                    # group's last-decided age (a stale re-report must
                    # not suppress a stalled-group report).
                    prev = self.m_decided.reshape(-1)[pidx_v]
                    fresh_cells = pidx_v[prev < 0]
                    if self._sub_groups:
                        feed_flat = fresh_cells
                        feed_vids = vals_v[prev < 0]
                    # np.put: flat scatter that cannot silently land in a
                    # reshape copy if the mirror ever goes non-contiguous.
                    np.put(self.m_decided, pidx_v, vals_v)
                    applied = len(pidx_v)
                    if len(fresh_cells):
                        self._g_last_decided[np.unique(
                            fresh_cells // (I * P))] = time.monotonic()
                if epoch < self._resync_epoch:
                    # Launched before an overflow resync: the absolute
                    # fetch already mirrored this dispatch's decisions.
                    ndec = int((self.m_decided >= 0).sum())
                    newly = ndec - self._decided_cells
                    self._decided_cells = ndec
                else:
                    newly = applied
                    self._decided_cells += applied
            if feed_flat is not None and len(feed_flat):
                # Before _gc_locked: the fed seqs must still be in the
                # slot map.  The feed self-times; split the retire timer
                # around it so phases don't double-count.
                self.profiler.add("retire", time.perf_counter_ns() - t_r,
                                  count=0)
                self._feed_cells_locked(feed_flat, feed_vids)
                t_r = time.perf_counter_ns()
            done_view = np.array(done_view)
            self.m_done_view = done_view
            pidx = np.arange(P)
            done_view[:, pidx, pidx] = np.maximum(
                done_view[:, pidx, pidx], self._done)
            np.minimum.reduce(done_view, axis=2, out=self._pmin_i32)
            self._peer_min = self._pmin_i32.astype(np.int64) + 1
            if proto is not None:
                self._fold_proto_locked(proto)
            self.events.bump("steps", self._spd)
            self.events.bump("msgs", int(msgs))
            if newly > 0:
                self.events.bump("decided_cells", newly)
                dprintf("fabric", "step %d: +%d decided cells, %d msgs",
                        self.steps_total, newly, int(msgs))
            self._max_seq = np.maximum(self._max_seq,
                                       maxseq.astype(np.int64))
            self._last_step_active = (
                n_inject > 0 or int(msgs) > 0 or newly > 0
                or self._live_slots * P > self._decided_cells)
            gc_drops = self._gc_locked()
            self._stepped.notify_all()
            self._last_retire_t = time.monotonic()
            self.profiler.add("retire", time.perf_counter_ns() - t_r)
            if n_inject > 0 or int(msgs) > 0 or newly > 0:
                # Activity-gated so an idle clock doesn't flood the
                # flight ring (the recorder is always on).
                obs_tracing.batch("fabric.retire.batch", t_r_mono,
                                  steps=self._spd, newly=int(newly),
                                  msgs=int(msgs))
        self._decref_many(gc_drops)

    def _step_once_compact(self):
        self._retire_compact(self._launch_compact())

    @property
    def steps_per_dispatch(self) -> int:
        return self._spd

    @property
    def pipeline_depth(self) -> int:
        return self._pipeline_depth

    @property
    def num_shards(self) -> int:
        """Mesh shards on the group axis (1 for single-device fabrics —
        the degradation contract's observable form)."""
        return self._plane.shards if self._plane is not None else 1

    def shard_of(self, g: int) -> int:
        """Mesh shard owning group `g` (always 0 off-mesh).  The service
        layer binds each kvpaxos/shardkv group to this at attach time —
        drain/opscope attribution and the frontend's cross-shard routing
        read the binding, never the mesh."""
        return self._plane.shard_of(g) if self._plane is not None else 0

    @property
    def live_slots(self) -> int:
        """Live instance-window cells across all groups — an advisory
        lock-free read (the horizon bounded-memory gauges sample it;
        one int load, no lock, staleness is free)."""
        return int(self._live_slots)

    def set_pipeline_depth(self, depth: int) -> None:
        """Live pipeline-depth churn (the nemesis uses this as a fault
        dimension): the free-running clock adapts on its next step_async —
        a shallower depth retires the in-flight surplus immediately, a
        deeper one lets more dispatches accumulate.  Safe concurrently
        with a running clock; direct step() calls stay synchronous."""
        self._pipeline_depth = max(1, int(depth))

    @property
    def clock_running(self) -> bool:
        with self._lock:
            return self._running

    @property
    def steps_total(self) -> int:
        return self.events.counters().get("steps", 0)

    @property
    def msgs_total(self) -> int:
        return self.events.counters().get("msgs", 0)

    def wait_steps(self, n: int, timeout: float = 30.0):
        """Block until the fabric has advanced n more steps."""
        with self._lock:
            target = self.steps_total + n
            deadline = time.monotonic() + timeout
            while self.steps_total < target:
                remaining = deadline - time.monotonic()
                if remaining <= 0 or not self._running:
                    break
                self._stepped.wait(remaining)

    # ---------------------------------------------------------------- GC

    def _global_min_locked(self, g: int) -> int:
        # min over peers of Min_p, where Min_p = 1 + min_q done_view[p, q]
        # (paxos/paxos.go:420-425).  Conservative: a slot may be recycled only
        # once *every* peer has forgotten it.
        return int(self._peer_min[g].min())

    def _gc_locked(self) -> list[int]:
        # Vectorized staleness scan: one (G, I) compare against the per-group
        # global min, instead of a Python dict walk per group per step.  The
        # common case (nothing to collect) costs one reduce + one any().
        # Returns the interned-value ids whose GC refs must be dropped —
        # the CALLER decrefs them after releasing the fabric lock (each
        # decref is a store call with its own mutex; at clerk-frontend
        # load the retire hold must not serialize on them).
        gmin = self._peer_min.min(axis=1)  # (G,)
        stale = (self._slot_seq >= 0) & (self._slot_seq < gmin[:, None])
        if not stale.any():
            return []
        gs, slots = np.nonzero(stale)
        seqs = self._slot_seq[gs, slots]
        # Array-side reclamation in bulk; only the dict/freelist/intern
        # bookkeeping stays a (minimal) Python loop.
        # Mirrors must stop reporting the old tenant immediately, and the
        # wiped cells are deducted from the running decided count so
        # decided_cells keeps crediting decisions that land in recycled
        # slots (steady-state windowed throughput).
        self._decided_cells -= int((self.m_decided[gs, slots, :] >= 0).sum())
        self.m_decided[gs, slots, :] = NO_VAL
        self._slot_seq[gs, slots] = -1
        self._pending_resets.extend(zip(gs.tolist(), slots.tolist()))
        self._live_slots -= len(gs)
        drops: list[int] = []
        for g, slot, seq in zip(gs.tolist(), slots.tolist(), seqs.tolist()):
            del self._seq2slot[g][seq]
            heapq.heappush(self._free[g], slot)
            fv = self._feed_vals[g]
            if fv:
                fv.pop(seq, None)  # decode cache lives per tenancy
            vids = self._slot_vids[g][slot]
            if vids:
                drops.extend(vids)
                self._slot_vids[g][slot] = []
        return drops

    # ---------------------------------------------------------------- API

    def _slot_for_locked(self, g: int, seq: int, create: bool) -> int | None:
        slot = self._seq2slot[g].get(seq)
        if slot is not None:
            return slot
        if not create:
            return None
        if not self._free[g]:
            raise WindowFullError(
                f"group {g}: all {self.I} instance slots live; "
                f"call Done() to advance Min() (global_min={self._global_min_locked(g)})"
            )
        # Smallest free slot (heap pop); a freed slot's pending reset (if
        # any) is applied before the start lands (apply_starts order), so
        # reuse is safe.
        slot = heapq.heappop(self._free[g])
        self._live_slots += 1
        self._slot_seq[g, slot] = seq
        self._seq2slot[g][seq] = slot
        self._slot_alloc_t[g, slot] = time.monotonic()
        return slot

    def start(self, g: int, p: int, seq: int, value) -> None:
        """paxos.Start(seq, v) for peer p of group g (paxos/paxos.go:99-109):
        asynchronous — agreement proceeds on subsequent clock steps."""
        with self._lock:
            self._start_locked(g, p, seq, value)

    def _start_locked(self, g: int, p: int, seq: int, value) -> None:
        if seq >= _SEQ_LIMIT:
            raise OverflowError(f"start seq {seq} exceeds int32")
        if self._dead[g, p]:
            return
        if seq < self._peer_min[g, p]:
            return  # forgotten; reference ignores such Starts
        slot = self._seq2slot[g].get(seq)
        if slot is not None and self.m_decided[g, slot, p] >= 0:
            return  # already decided locally; nothing to do
        # Allocate the slot BEFORE interning: _slot_for_locked may raise
        # WindowFullError, and an intern ref taken first would never be
        # decref'd (leak under start-retry backpressure loops).
        slot = self._slot_for_locked(g, seq, create=True)
        if type(value) is int and 0 <= value < IMM_BASE:
            vid = IMM_BASE | value  # immediate: no store, no refcount
        else:
            vid = self.intern.put(value)
            self._slot_vids[g][slot].append(vid)
        self._pending_starts.append((g, slot, p, vid, seq))
        self._clock_wake.set()
        if seq > self._max_seq[g, p]:
            self._max_seq[g, p] = seq

    def status(self, g: int, p: int, seq: int):
        """paxos.Status (paxos/paxos.go:434-447) → (Fate, value)."""
        from tpu6824.core.peer import Fate

        with self._lock:
            if seq < self._peer_min[g, p]:
                return Fate.FORGOTTEN, None
            slot = self._seq2slot[g].get(seq)
            if slot is None:
                return Fate.PENDING, None
            vid = int(self.m_decided[g, slot, p])
            if vid < 0:
                return Fate.PENDING, None
            if vid >= IMM_BASE:
                return Fate.DECIDED, vid - IMM_BASE
            return Fate.DECIDED, self.intern.get(vid)

    # ----------------------------------------------------- batched API
    # The fabric is a batched runtime: a driver pumping hundreds of groups
    # per clock step should pay one lock acquisition per batch, not per op.
    # Semantics are exactly N calls of the scalar methods, in order.

    def start_many(self, ops) -> None:
        """Batched Start: `ops` iterates (g, p, seq, value).

        Semantically N scalar start() calls; the body is the same logic with
        the per-op numpy-scalar reads hoisted to plain-int lists (this is
        the service driver's hottest call).  Payloads are interned BEFORE
        the fabric lock is taken: pickle + store call are the loop's
        dominant per-op cost, and under the lock they serialized every
        driver behind the clock's retire fold (sampled at ~47% of busy
        time on the clerk-frontend path); refs taken for ops the locked
        pass then skips are dropped after release.

        NOT atomic: on WindowFullError the prefix ops[:e.index] has been
        applied and the rest dropped — resume the batch from `e.index`
        after GC frees slots (retrying from 0 is safe but re-queues the
        prefix).  The same contract holds for the `fabric_service`
        start_many RPC."""
        ops = ops if isinstance(ops, list) else list(ops)
        put = self.intern.put
        vids_pre = [
            (IMM_BASE | value)
            if type(value) is int and 0 <= value < IMM_BASE
            else put(value)
            for (_g, _p, _seq, value) in ops
        ]
        drop: list[int] = []
        try:
            self._start_many_locked(ops, vids_pre, drop)
        finally:
            self._decref_many(drop)
            # Even a WindowFullError mid-batch pended a prefix: wake the
            # idle clock so backpressure-retry loops never pay the idle
            # sleep.
            self._clock_wake.set()

    def _decref_many(self, vids) -> None:
        """Drop a batch of interning refs OUTSIDE the fabric lock — the
        store has its own mutex (see _gc_locked / start_many)."""
        if vids:
            decref = self.intern.decref
            for vid in vids:
                decref(vid)

    def _start_many_locked(self, ops, vids_pre, drop) -> None:
        """The locked half of start_many: slot allocation + staging.
        `vids_pre[n]` is op n's pre-interned value id (one ref owned by
        this batch); a skipped or never-reached op's ref is pushed onto
        `drop` for the caller to release outside the lock."""
        n = -1
        try:
            with self._lock:
                dead = self._dead.tolist()
                pmin = self._peer_min.tolist()
                s2s = self._seq2slot
                item = self.m_decided.item
                free = self._free
                slot_seq = self._slot_seq
                vids = self._slot_vids
                pend = self._pending_starts.append
                mx = self._max_seq
                alloc_t = self._slot_alloc_t
                now = time.monotonic()  # batch-granular: plenty for health
                for n, (g, p, seq, value) in enumerate(ops):
                    vid = vids_pre[n]
                    if seq >= _SEQ_LIMIT:
                        raise OverflowError(
                            f"start seq {seq} exceeds int32 "
                            f"(batch applied up to index {n})")
                    if dead[g][p] or seq < pmin[g][p]:
                        if vid < IMM_BASE:
                            drop.append(vid)
                        continue
                    slot = s2s[g].get(seq)
                    if slot is not None:
                        if item(g, slot, p) >= 0:
                            if vid < IMM_BASE:
                                drop.append(vid)
                            continue  # already decided locally
                    else:
                        fl = free[g]
                        if not fl:
                            raise WindowFullError(
                                f"group {g}: all {self.I} instance slots "
                                f"live; call Done() to advance Min() "
                                f"(global_min="
                                f"{self._global_min_locked(g)}); "
                                f"batch applied up to index {n}",
                                index=n)
                        slot = heapq.heappop(fl)
                        self._live_slots += 1
                        slot_seq[g, slot] = seq
                        s2s[g][seq] = slot
                        alloc_t[g, slot] = now
                    if vid < IMM_BASE:
                        vids[g][slot].append(vid)
                    pend((g, slot, p, vid, seq))
                    if seq > mx[g, p]:
                        mx[g, p] = seq
        except (OverflowError, WindowFullError):
            # Ops the raise cut off never consumed their pre-taken ref.
            drop.extend(v for v in vids_pre[max(n, 0):] if v < IMM_BASE)
            raise

    def status_many(self, queries) -> list:
        """Batched Status: `queries` iterates (g, p, seq); returns a
        (Fate, value) list in query order."""
        from tpu6824.core.peer import Fate

        out = []
        append = out.append
        forgotten = (Fate.FORGOTTEN, None)
        pending = (Fate.PENDING, None)
        decided = Fate.DECIDED
        with self._lock:
            # Hot loop: everything hoisted; pmin as a plain nested list so
            # the per-query compare is int-vs-int, not a numpy scalar.
            pmin = self._peer_min.tolist()
            dec = self.m_decided
            item = dec.item
            s2s = self._seq2slot
            get = self.intern.get
            for g, p, seq in queries:
                if seq < pmin[g][p]:
                    append(forgotten)
                    continue
                slot = s2s[g].get(seq)
                vid = -1 if slot is None else item(g, slot, p)
                if vid < 0:
                    append(pending)
                elif vid >= IMM_BASE:
                    append((decided, vid - IMM_BASE))
                else:
                    append((decided, get(vid)))
        return out

    def drain_decided(self, g: int, p: int, lo: int, max_n: int = 256):
        """Bulk RSM drain: the values of the contiguous DECIDED prefix
        starting at seq `lo` for peer p of group g — one lock acquisition
        and one numpy pass instead of up to `max_n` status() dict walks
        (the hot half of the reference's sync loop,
        kvpaxos/server.go:69-113, vectorized).

        Returns (values, next_seq, forgotten): `values` are the decided
        payloads for seqs [lo, next_seq); `forgotten=True` means `lo` is
        already below Min() for this peer (caller must recover via its
        FORGOTTEN path).  Stops at the first gap (undecided or
        unallocated seq), exactly like a status() walk would."""
        with self._lock:
            if lo < self._peer_min[g, p]:
                return [], lo, True
            ss = self._slot_seq[g]
            mask = (ss >= lo) & (ss < lo + max_n)
            if not mask.any():
                return [], lo, False
            slots = np.nonzero(mask)[0]
            seqs = ss[slots]
            order = np.argsort(seqs)
            slots = slots[order]
            seqs = seqs[order]
            vids = self.m_decided[g, slots, p]
            good = (seqs == np.arange(lo, lo + len(seqs))) & (vids >= 0)
            k = len(good) if good.all() else int(np.argmin(good))
            if k == 0:
                return [], lo, False
            get = self.intern.get
            out = [vid - IMM_BASE if vid >= IMM_BASE else get(vid)
                   for vid in vids[:k].tolist()]
            return out, lo + k, False

    # ------------------------------------------------- decided-delta feed

    def subscribe_decided(self, g: int, p: int, wake=None) -> DecidedSub:
        """Subscribe to peer p of group g's decided deltas.

        The returned sub's queue is SEEDED with everything this peer has
        already decided (mirror state at subscription time), so feed
        consumption is complete from any subscription point — a server
        booted onto a warm or checkpoint-restored fabric catches up from
        the seed, then rides the deltas.  Values are decoded through the
        group's decode-once cache either way."""
        sub = DecidedSub(self, g, p, wake=wake)
        with self._lock:
            self._subs.setdefault((g, p), []).append(sub)
            self._sub_groups.add(g)
            ss = self._slot_seq[g]
            live = (ss >= 0) & (self.m_decided[g, :, p] >= 0)
            if live.any():
                slots = np.nonzero(live)[0]
                seqs = ss[slots]
                order = np.argsort(seqs)
                vids = self.m_decided[g, slots[order], p]
                sq = seqs[order].tolist()
                decode = self._feed_decode_locked
                sub._q.append(
                    (sq, [decode(g, s, int(v))
                          for s, v in zip(sq, vids.tolist())]))
                sub.delivered += len(slots)
        return sub

    def unsubscribe_decided(self, sub: DecidedSub) -> None:
        with self._lock:
            lst = self._subs.get((sub.g, sub.p))
            if lst is not None:
                try:
                    lst.remove(sub)
                except ValueError:
                    pass
                if not lst:
                    del self._subs[sub.g, sub.p]
            if not any(g == sub.g for g, _ in self._subs):
                self._sub_groups.discard(sub.g)

    def _feed_decode_locked(self, g: int, seq: int, vid: int):
        """vid → payload through the per-group decode-once cache.
        Immediate-tagged ids carry their own payload (no store, nothing to
        cache); interned ids hit `intern.get` exactly once per (g, seq)
        tenancy — the cache entry lives until the window GC forgets the
        seq, so stragglers (a deafened peer deciding retires later) reuse
        the decode instead of repeating it."""
        if vid >= IMM_BASE:
            return vid - IMM_BASE
        cache = self._feed_vals[g]
        val = cache.get(seq, cache)  # sentinel: cached None is a value
        if val is cache:
            val = self.intern.get(vid)
            cache[seq] = val
        return val

    def _feed_cells_locked(self, flat_cells, vids) -> None:
        """Fan newly-decided cells (flat (G·I·P) indices + their value
        ids) out to subscriber queues.  Caller guarantees every cell is a
        FRESH mirror transition (<0 → >=0), so a (g, p, seq) is delivered
        at most once per tenancy; seqs come from the host slot map, which
        the tenancy filter has already validated.

        COLUMNAR on purpose: cells are grouped per (g, p) with one stable
        sort, values decoded per run (cache makes it once per (g, seq)),
        and each subscriber receives ONE (seqs, values) batch per retire.
        The first cut did a per-cell Python loop with per-cell queue
        appends and spent ~160ms per retire under the fabric lock at
        clerk-bench shape (48 groups × 64-wide waves ≈ 9k cells/retire),
        stalling every start_many/status_many caller behind it."""
        if not self._sub_groups or not len(flat_cells):
            return
        t0 = time.perf_counter_ns()
        G, I, P = self.G, self.I, self.P
        gs = flat_cells // (I * P)
        if len(self._sub_groups) < G:
            keep = np.isin(gs, np.fromiter(self._sub_groups, np.int64,
                                           len(self._sub_groups)))
            if not keep.all():
                flat_cells = flat_cells[keep]
                vids = vids[keep]
                gs = gs[keep]
        rem = flat_cells - gs * (I * P)
        slots = rem // P
        ps = rem - slots * P
        seqs = self._slot_seq[gs, slots]
        ok = seqs >= 0
        if not ok.all():
            gs, ps, seqs, vids = gs[ok], ps[ok], seqs[ok], vids[ok]
        if not len(gs):
            self.profiler.add("feed", time.perf_counter_ns() - t0)
            return
        key = gs * P + ps
        order = np.argsort(key, kind="stable")
        key_o = key[order]
        seqs_o = seqs[order]
        vids_o = vids[order]
        bounds = np.flatnonzero(np.diff(key_o)) + 1
        starts = np.concatenate(([0], bounds)).tolist()
        ends = np.concatenate((bounds, [len(key_o)])).tolist()
        subs = self._subs
        decode = self._feed_decode_locked
        woken: list[DecidedSub] = []
        tr = obs_tracing.enabled()
        t0_mono = time.monotonic_ns() if tr else 0
        n = 0
        for a, b in zip(starts, ends):
            g, p = divmod(int(key_o[a]), P)
            lst = subs.get((g, p))
            if not lst:
                continue  # decode lazily: only cells a subscriber consumes
            sq = seqs_o[a:b].tolist()
            vals = [decode(g, s, v) for s, v in zip(sq, vids_o[a:b].tolist())]
            # tpusan: ok(lock-nested-loop) — iterates per (g, p) RUN ×
            # subscriber, never per cell: each sub gets ONE columnar
            # (seqs, values) batch append (the TUNING round-7 contract).
            for sub in lst:
                sub._q.append((sq, vals))
                sub.delivered += b - a
                n += b - a
                if sub.wake is not None:
                    woken.append(sub)  # one run per (g, p): no dups
            if tr:
                # Per-(g, p) feed span, ONE per run (never per cell) —
                # tracing-gated so the default hot path records nothing.
                obs_tracing.batch("fabric.feed", t0_mono, g=g, p=p,
                                  cells=b - a)
        if n:
            self.events.bump("feed_delivered", n)
            # Columnar registry update: one histogram observation per
            # retire's whole fan-out, never per cell.
            _M_FEED_BATCH.observe(n)
        for sub in woken:
            sub.wake()
        self.profiler.add("feed", time.perf_counter_ns() - t0)

    def done_many(self, items) -> None:
        """Batched Done: `items` iterates (g, p, seq) — one vectorized
        update + one row-min recompute per affected group, instead of a
        per-call row reduction (the RSM drain calls Done once per applied
        op per peer; this is the fabric's hottest write path)."""
        items = items if isinstance(items, list) else list(items)
        if not items:
            return
        arr = np.asarray(items, dtype=np.int64)
        if (arr[:, 2] >= np.int64(2) ** 31).any():
            raise OverflowError("done seq exceeds int32 (matches scalar "
                                "done()'s loud failure)")
        gs, ps, seqs = arr[:, 0], arr[:, 1], arr[:, 2].astype(np.int32)
        with self._lock:
            np.maximum.at(self._done, (gs, ps), seqs)
            # Own view updates without needing a message to self.
            np.maximum.at(self.m_done_view, (gs, ps, ps), seqs)
            gu = np.unique(gs)
            self._peer_min[gu] = (
                self.m_done_view[gu].min(axis=2).astype(np.int64) + 1)

    def done(self, g: int, p: int, seq: int) -> None:
        """paxos.Done (paxos/paxos.go:352-359)."""
        with self._lock:
            self._done_locked(g, p, seq)

    def done_deferred(self, g: int, p: int, seq: int) -> None:
        """Lock-free Done: record the watermark into the async staging
        array; the clock folds it at its next dispatch staging.  Done is
        an advisory GC floor, so one dispatch of staleness is always
        safe — and the caller (a hot RSM driver) never convoys behind a
        retire fold holding the fabric lock (sampled at ~11% of busy
        time on the clerk-frontend path before this existed)."""
        if seq > self._done_async[g, p]:
            self._done_async[g, p] = seq

    def _fold_done_async_locked(self) -> None:
        """Fold done_deferred watermarks into _done / own done-view /
        peer_min — called at dispatch staging, before _done ships to the
        device for gossip."""
        pend = self._done_async
        mask = pend > self._done
        if not mask.any():
            return
        np.maximum(self._done, pend, out=self._done)
        gs, ps = np.nonzero(mask)
        self.m_done_view[gs, ps, ps] = np.maximum(
            self.m_done_view[gs, ps, ps], self._done[gs, ps])
        self._peer_min[gs, ps] = self.m_done_view[gs, ps].min(axis=1) + 1

    def _done_locked(self, g: int, p: int, seq: int) -> None:
        if seq > self._done[g, p]:
            self._done[g, p] = seq
            # Own view updates without needing a message to self.
            if seq > self.m_done_view[g, p, p]:
                self.m_done_view[g, p, p] = seq
                self._peer_min[g, p] = int(self.m_done_view[g, p].min()) + 1

    def peer_min(self, g: int, p: int) -> int:
        """paxos.Min (paxos/paxos.go:420-425): 1 + min over peers of done as
        known to p via piggybacked/heartbeat traffic."""
        with self._lock:
            return int(self._peer_min[g, p])

    def peer_max(self, g: int, p: int) -> int:
        """paxos.Max (paxos/paxos.go:385-390)."""
        with self._lock:
            return int(self._max_seq[g, p])

    # ------------------------------------------------------- network control

    def set_unreliable(self, flag: bool, g: int | None = None, p: int | None = None):
        """Per-receiving-server message loss (the accept-loop coin flips,
        paxos/paxos.go:528-544)."""
        with self._lock:
            gs = slice(None) if g is None else g
            ps = slice(None) if p is None else p
            self._unreliable[gs, ps] = flag

    def partition(self, g: int, *parts: list[int]):
        """Split group g's peers into disjoint partitions; traffic flows only
        within a partition (the socket hard-link farm,
        paxos/test_test.go:712-751).  Peers not listed are fully isolated."""
        with self._lock:
            self._link_dev = None
            self._link[g] = False
            for part in parts:
                # tpusan: ok(lock-nested-loop) — P×P over one group's peers
                # (single digits) on the cold network-control path; the hot
                # path only reads the resulting mask.
                for a in part:
                    # tpusan: ok(lock-nested-loop) — same P×P bound as above
                    for b in part:
                        self._link[g, a, b] = True
            # Socket surgery must not resurrect a crashed peer (heal() has
            # the same guard): dead lanes stay cut whatever the partition.
            self._apply_dead_locked(g)

    def heal(self, g: int | None = None):
        with self._lock:
            self._link_dev = None
            if g is None:
                self._link[:] = True
            else:
                self._link[g] = True
            for gg in range(self.G) if g is None else [g]:
                self._apply_dead_locked(gg)

    def deafen(self, g: int, p: int):
        """Nothing can be delivered TO peer p (socket file removed,
        paxos/test_test.go:194-195); p can still send."""
        with self._lock:
            self._link_dev = None
            self._link[g, :, p] = False

    def set_link(self, g: int, src: int, dst: int, up: bool):
        with self._lock:
            self._link_dev = None
            self._link[g, src, dst] = up

    def _apply_dead_locked(self, g: int):
        for p in range(self.P):
            if self._dead[g, p]:
                self._link[g, :, p] = False
                self._link[g, p, :] = False

    def kill(self, g: int, p: int):
        """Crash peer p of group g (paxos.Kill, paxos/paxos.go:456-461): no
        more sends or receives; its state is NOT recovered (the reference
        Paxos has no persistence)."""
        with self._lock:
            self._link_dev = None
            self._dead[g, p] = True
            self._apply_dead_locked(g)

    def revive(self, g: int, p: int):
        """Reboot a crashed peer (diskv's restart path): clears the dead flag
        and restores its links, leaving other peers' crash state intact."""
        with self._lock:
            self._link_dev = None
            self._dead[g, p] = False
            self._link[g, p, :] = True
            self._link[g, :, p] = True
            self._apply_dead_locked(g)

    def is_dead(self, g: int, p: int) -> bool:
        with self._lock:
            return bool(self._dead[g, p])

    # ------------------------------------------------------- checkpoint

    def set_recovery_info(self, **kw) -> None:
        """Merge durability/recovery status into stats()["health"]
        ["recovery"] — written by PaxosFabric.restore (recovery_time_s,
        source) and by the continuous checkpointer daemon
        (core/checkpointd.py: snapshot age/bytes/seq, truncated
        horizon).  One dict so the harness has ONE window on "how stale
        is the newest durable image and how long did the last recovery
        take"."""
        with self._lock:
            self._recovery.update(kw)

    @staticmethod
    def _start_is_live(slot_seq, t, known_vids=None) -> bool:
        """Keep predicate for a queued (g, slot, p, vid, seq) start: its
        slot still maps to its seq (the vectorized form of this same test
        gates the live drain in _step_once).  With `known_vids`, also
        require the vid to have a payload (restore-side defense against
        pre-fix blobs).  One definition, three users — do not fork it."""
        g, s, _p, v, seq = t
        if slot_seq[g, s] != seq:
            return False
        return known_vids is None or v >= IMM_BASE or v in known_vids

    def checkpoint(self, path: str) -> None:
        """Snapshot the ENTIRE consensus universe — device state, host
        mirrors, slot/window bookkeeping, network condition, queued ops,
        and every live value payload — to one checksummed file, with the
        full durafs crash-consistency discipline (tmp fsync + rename +
        dir fsync; `utils/durafs.py`).

        The reference's paxos is explicitly not crash-safe
        (paxos/paxos.go:3-11); its persistence story lives in diskv and in
        `HostPaxosPeer(persist_dir=...)`.  This is the batched-runtime
        analog: checkpoint/resume for all G groups at once, the way an ML
        framework checkpoints a training state pytree.

        Must be called with the clock stopped (deterministic snapshot —
        a step in flight would leave device state and mirrors torn); the
        continuous checkpointer (`core/checkpointd.py`) wraps the pause
        so live traffic only waits out the state COPY, not the pickle or
        the disk write.
        """
        import pickle

        blob = self.snapshot_blob()
        payload = pickle.dumps(blob, protocol=pickle.HIGHEST_PROTOCOL)
        durafs.atomic_write(path, frame_checkpoint(payload))

    def snapshot_blob(self) -> dict:
        """The copy half of checkpoint(): every array/queue copied under
        the lock into a self-contained dict (nothing aliases live fabric
        state), so serialization and IO can run OFF the lock while other
        API threads — or a restarted clock — keep going."""
        with self._lock:
            # Guard BEFORE flushing: flush races a live clock thread's
            # step_async on the in-flight deque — the misuse must raise
            # without touching anything.
            if self._running:
                raise RuntimeError("stop_clock() before checkpoint()")
        self.flush()  # retire any step_async() dispatches still in flight
        with self._lock:
            if self._running:
                raise RuntimeError("stop_clock() before checkpoint()")
            self._fold_done_async_locked()  # deferred Done → the snapshot
            # Mesh fabrics read each leaf shard-locally (per-shard column
            # pulls, core/fabdev.py::fetch_host) — the snapshot never
            # triggers a cross-device all-gather.
            fetch = (self._plane.fetch_host if self._plane is not None
                     else np.array)
            state_np = {f: fetch(x)
                        for f, x in zip(self._state._fields, self._state)}
            # Pending window-GC resets are applied INTO the snapshot (their
            # effect is deterministic): the device arrays may still carry
            # value ids whose intern refs the GC already dropped — those
            # cells must not reach restore()'s vid remap.
            if self._pending_resets:
                r = np.asarray(self._pending_resets)
                gs, ss = r[:, 0], r[:, 1]
                for f, fill in (("np_", 0), ("na", 0), ("va", NO_VAL),
                                ("decided", NO_VAL), ("active", False),
                                ("propv", NO_VAL), ("maxseen", 0)):
                    state_np[f][gs, ss, :] = fill
            # Live payloads: every vid referenced by any slot or queued op
            # (immediate-tagged ids carry their own payload; see IMM_BASE).
            vids = sorted({v for g in range(self.G)
                           for slot in self._slot_vids[g]
                           for v in slot})
            # Everything below is COPIED under the lock: the blob must not
            # alias mutable fabric state (serialization happens outside
            # the lock, and other API threads stay free to run).
            blob = {
                "dims": (self.G, self.I, self.P),
                "kernel": self._kernel_req,
                "io_mode": self._io_mode,
                "drops": (self._req_drop, self._rep_drop),
                "state": state_np,
                "link": self._link.copy(),
                "unreliable": self._unreliable.copy(),
                "done": self._done.copy(), "dead": self._dead.copy(),
                "m_decided": self.m_decided.copy(),
                "m_done_view": self.m_done_view.copy(),
                "max_seq": self._max_seq.copy(),
                "slot_seq": self._slot_seq.copy(),
                "seq2slot": [dict(d) for d in self._seq2slot],
                "free": [list(s) for s in self._free],
                "slot_vids": [[list(v) for v in grp]
                              for grp in self._slot_vids],
                "values": {v: self.intern.get(v) for v in vids},
                # _start_is_live: a start queued mid-step whose slot the
                # end-of-step GC recycled still sits in the queue with a
                # decref'd vid — snapshotting it verbatim would make the
                # file unrestorable (restore()'s vid remap lacks it).
                "pending_starts": [
                    t for t in self._pending_starts
                    if self._start_is_live(self._slot_seq, t)],
                "pending_resets": [],  # applied into the snapshot above
                "key_data": np.array(jax.random.key_data(self._key)),
            }
        return blob

    @classmethod
    def restore(cls, path: str, **kw) -> "PaxosFabric":
        """Resume a checkpointed fabric.  Interned value ids are REMAPPED
        through a fresh intern store (so either intern backend restores
        into either), with the device arrays rewritten through the same
        old→new lookup; immediate-tagged ids pass through unchanged.
        PRNG subkey batching restarts at the saved base key, so post-
        restore lossy draws differ from an uninterrupted run (determinism
        holds per process lifetime, not across the boundary).

        The file's checksum frame is VERIFIED first: a torn or truncated
        checkpoint raises `CorruptCheckpointError` instead of restoring
        garbage (the recovery scanner in core/checkpointd.py turns that
        into "discard and fall back to the previous snapshot").  Unframed
        files from before the durafault PR still load (raw pickle)."""
        import pickle

        t0 = time.monotonic()
        with open(path, "rb") as f:
            raw = f.read()
        blob = pickle.loads(unframe_checkpoint(raw, path=path))
        G, I, P = blob["dims"]
        kw.setdefault("kernel", blob["kernel"])
        if blob.get("io_mode"):
            kw.setdefault("io_mode", blob["io_mode"])
        kw.setdefault("unreliable_req_drop", blob["drops"][0])
        kw.setdefault("unreliable_rep_drop", blob["drops"][1])
        # The clock must not run while state is being swapped in.
        auto_step = kw.pop("auto_step", False)
        fab = cls(ngroups=G, npeers=P, ninstances=I, **kw)
        with fab._lock:
            # Rebuild the intern with exactly one ref per _slot_vids entry
            # (the GC decrefs one per entry), building the old->new map —
            # any device vid absent from it fails LOUDLY in remap (the
            # checkpoint invariant is that no such vid exists).
            old2new = {}
            new_vids = [[[] for _ in range(I)] for _ in range(G)]
            for g in range(G):
                # tpusan: ok(lock-nested-loop) — boot-time restore, clock
                # not yet running; nothing contends for the lock.
                for slot in range(I):
                    # tpusan: ok(lock-nested-loop) — same boot-time bound
                    for old_vid in blob["slot_vids"][g][slot]:
                        nv = fab.intern.put(blob["values"][old_vid])
                        old2new[old_vid] = nv
                        new_vids[g][slot].append(nv)
            fab._slot_vids = new_vids

            def remap(a):
                a = np.array(a)
                m = (a >= 0) & (a < IMM_BASE)
                if m.any():
                    a[m] = np.vectorize(
                        lambda v: old2new[v], otypes=[np.int64])(a[m])
                return a

            st = {f: np.array(v) for f, v in blob["state"].items()}
            for f in ("va", "decided", "propv"):
                st[f] = remap(st[f]).astype(st[f].dtype)
            fab._state = type(fab._state)(**{
                f: jnp.asarray(v) for f, v in st.items()})
            if fab._plane is not None:
                fab._state = fab._plane.place_state(fab._state)
            fab._link = np.array(blob["link"])
            fab._link_dev = None
            fab._unreliable = np.array(blob["unreliable"])
            fab._done = np.array(blob["done"])
            fab._dead = np.array(blob["dead"])
            fab.m_decided = remap(blob["m_decided"]).astype(np.int32)
            fab.m_done_view = np.array(blob["m_done_view"])
            np.minimum.reduce(fab.m_done_view, axis=2, out=fab._pmin_i32)
            fab._peer_min = fab._pmin_i32.astype(np.int64) + 1
            fab._max_seq = np.array(blob["max_seq"])
            fab._slot_seq = np.array(blob["slot_seq"])
            # Health clocks restart at the restore instant: a restored
            # undecided slot must age from NOW, not from epoch 0.
            fab._slot_alloc_t[:] = time.monotonic()
            if fab._io_mode == "compact":
                ss = jnp.asarray(fab._slot_seq.astype(np.int32))
                if fab._plane is not None:
                    ss = fab._plane.place_slot_seq(ss)
                fab._slot_seq_dev = ss
            fab._seq2slot = [dict(d) for d in blob["seq2slot"]]
            # Pre-heap blobs stored LIFO lists; heapify restores the
            # smallest-first allocation invariant either way.
            fab._free = [list(s) for s in blob["free"]]
            for fl in fab._free:
                heapq.heapify(fl)
            fab._live_slots = G * I - sum(len(s) for s in fab._free)
            fab._decided_cells = int((fab.m_decided >= 0).sum())
            # Defensive twin of checkpoint()'s keep-filter (pre-fix blobs
            # may carry GC-orphaned entries): same _start_is_live test,
            # plus the vid-has-a-payload check.
            fab._pending_starts = [
                (g, s, p, v if v >= IMM_BASE else old2new[v], seq)
                for g, s, p, v, seq in blob["pending_starts"]
                if cls._start_is_live(fab._slot_seq, (g, s, p, v, seq),
                                      old2new)]
            fab._pending_resets = list(blob["pending_resets"])
            fab._key = jax.random.wrap_key_data(jnp.asarray(blob["key_data"]))
            fab._key_arr = None
            fab._key_buf_n = 0
        dt = round(time.monotonic() - t0, 6)
        _M_RECOVERY_TIME.set(dt)
        fab.set_recovery_info(
            restored_from=os.path.basename(path), recovery_time_s=dt,
            decided_at_restore=int(fab._decided_cells))
        if auto_step:
            fab.start_clock()
        return fab

    # ------------------------------------------------------------- stats

    def _fold_proto_locked(self, proto) -> None:
        """Fold one dispatch's (G, NPROTO) protocol event counts into the
        host mirror and refresh the registry's process-wide protocol
        gauges.  Additive per dispatch, so totals stay exact under any
        pipeline depth and across overflow resyncs — every dispatch
        reports its own events exactly once, in its own summary.  The
        stall-diagnosis window buckets roll HERE (single writer: the
        clock thread) so reads never mutate window state."""
        p64 = proto.astype(np.int64)
        self._proto += p64
        self._proto_version += 1
        now = time.monotonic()
        if now - self._proto_bucket_t >= self._proto_window:
            self._proto_bucket_prev = self._proto_bucket_cur
            self._proto_bucket_cur = np.zeros_like(self._proto)
            self._proto_bucket_t = now
        self._proto_bucket_cur += p64
        tot = self._proto.sum(axis=0)
        for k, f in enumerate(PROTO_FIELDS):
            _M_PROTO[f].set(int(tot[k]))

    def _protocol_locked(self) -> dict:
        """stats()["protocol"]: the kernelscope per-group protocol
        counters plus the derived ratios ROADMAP items 2–3 judge variants
        by — rounds-per-decide (how many prepare rounds a decide actually
        cost) and the fast-path fraction (decides won at the proposer's
        first proposal number, the 1-round cohort flexible quorums
        target)."""
        tot = self._proto.sum(axis=0)
        totals = {f: int(tot[k]) for k, f in enumerate(PROTO_FIELDS)}
        # The per_group block boxes 7×G Python ints (G can be 1024);
        # cache it keyed by the fold version so idle-time polls (health
        # scrapes, fleet collectors) rebuild it only after a dispatch
        # actually folded new events.
        if self._protocol_cache is None or \
                self._protocol_cache[0] != self._proto_version:
            self._protocol_cache = (self._proto_version, {
                f: self._proto[:, k].tolist()
                for k, f in enumerate(PROTO_FIELDS)})
        return {
            "enabled": PROTO_ENABLED,
            "fields": list(PROTO_FIELDS),
            "totals": totals,
            "per_group": self._protocol_cache[1],
            # One derivation for per-fabric AND fleet-merged ratios
            # (obs.collector.derive_protocol_ratios): a variant PR that
            # redefines a cohort changes both or neither.
            **obs_collector.derive_protocol_ratios(totals),
        }

    @staticmethod
    def _diagnose_stall(d) -> str:
        """One stalled group's diagnosis from its protocol-event DELTA
        over the last health window — the difference between "the group
        cannot reach a majority" and "nobody is proposing", which the
        pre-kernelscope health block could not tell apart."""
        if not PROTO_ENABLED:
            return ("stalled: protocol counters disabled (TPU6824_PROTO"
                    "=0) — no protocol evidence to diagnose with")
        att = int(d[PROTO_FIELDS.index("prepare_attempts")])
        qf = int(d[PROTO_FIELDS.index("quorum_failures")])
        dec = int(d[PROTO_FIELDS.index("decides")])
        rst = int(d[PROTO_FIELDS.index("restarts")])
        if att == 0:
            return ("stalled: no proposals arriving — nothing armed this "
                    "window (starved driver/clerk path, or the clock is "
                    "not advancing)")
        if qf > 0 and dec == 0:
            return ("stalled: quorum failures climbing with zero decides "
                    "— no reachable majority (minority partition or too "
                    "many peers dead)")
        if rst > 0 and dec == 0:
            return ("stalled: proposers restarting without deciding — "
                    "dueling proposers or heavy message loss")
        return ("stalled: protocol active but undecided instances are "
                "aging — window backpressure or a slow consumer")

    def stats(self, stall_after: float | None = None) -> dict:
        """Live counters: steps, remote messages, decided cells, and their
        per-second rates — the decided/sec counter SURVEY §5 asks for —
        plus the host-side phase breakdown (stage/dispatch/retire/feed and,
        when services drive this fabric, their apply/notify legs) and the
        graceful-degradation health block (see _health_locked)."""
        counters = self.events.counters()
        with self._lock:
            out = {
                "steps": counters.get("steps", 0),
                "msgs": counters.get("msgs", 0),
                "decided_cells": self._decided_cells,
                "groups": self.G,
                "instances": self.I,
                "peers": self.P,
                "feed": {
                    "subscribers": sum(len(v) for v in self._subs.values()),
                    "delivered": counters.get("feed_delivered", 0),
                },
                # EventLog ring overflow, surfaced per the no-silent-caps
                # rule (the ring capacity knob is TPU6824_EVENTLOG_CAP).
                "events_dropped": counters.get("dropped", 0),
                # kernelscope device-resident protocol counters (per-group
                # + totals + derived ratios; see _protocol_locked).
                "protocol": self._protocol_locked(),
                "health": self._health_locked(
                    _STALL_AFTER if stall_after is None else stall_after),
            }
        out["rates"] = self.events.rates()
        out["phases"] = PhaseProfiler.breakdown(self.profiler.snapshot())
        # Refresh the registry's fabric-health gauges at every poll —
        # stats() is the harness's health window, so the registry's view
        # is exactly as fresh as the last poll.
        h = out["health"]
        _M_DECIDED.set(out["decided_cells"])
        _M_FEED_DEPTH.set(h["feed_depth_max"])
        _M_STALLED.set(len(h["stalled_groups"]))
        return out

    def metrics(self) -> dict:
        """The process-global tpuscope metrics snapshot (obs/metrics.py)
        — exported over the fabric_service wire next to stats(), so one
        poller sees RPC transport, clerk, service, and fabric counters
        in a single JSON shape."""
        return obs_metrics.snapshot()

    def flight(self) -> dict:
        """The process-global flight-recorder dump (obs/tracing.py) —
        served over the fabric_service wire so the kernelscope fleet
        collector can merge every process's recent spans/events into one
        Perfetto timeline (each process's records are namespaced by the
        collector; see obs/collector.py)."""
        return obs_tracing.flight_snapshot()

    def pulse(self) -> dict:
        """The process-global pulse time-series snapshot (obs/pulse.py)
        — counters-as-rates, gauges, and per-interval latency
        percentiles in bounded rings — served over the fabric_service
        wire so `obs.top` and the fleet collector see throughput OVER
        TIME, not just the instant's totals.  A stable `enabled: False`
        shell when no pulse is running in this process."""
        return obs_pulse.series_snapshot()

    def opscope(self) -> dict:
        """The process-global opscope waterfall snapshot (obs/opscope.py,
        ISSUE 15) — per-stage latency histograms of the request path,
        served over the fabric_service wire so `obs.top`'s waterfall
        pane and the fleet collector can merge per-stage attribution
        across processes.  A stable `enabled: False` shell when opscope
        is disabled in this process."""
        from tpu6824.obs import opscope as obs_opscope

        return obs_opscope.snapshot()

    def blackbox(self) -> dict:
        """The process-global blackbox recorder status (obs/blackbox.py,
        ISSUE 20) — ring path, seal count, bytes written — served over
        the fabric_service wire so the fleet collector can report which
        members are flight-recording and where their rings live.  A
        stable `enabled: False` shell when no recorder runs here."""
        from tpu6824.obs import blackbox as obs_blackbox

        return obs_blackbox.status()

    def start_pulse(self, interval: float | None = None,
                    cap: int | None = None,
                    stall_after: float | None = None):
        """Start (or return) the process pulse sampling THIS fabric —
        the health wiring fabricd's `--pulse` flag uses.  Each tick
        polls stats() (a pure read), so the registry's health gauges and
        the watchdog's stall evidence stay one interval fresh."""
        return obs_pulse.start(fabric=self, interval=interval, cap=cap,
                               stall_after=stall_after)

    def _health_locked(self, stall_after: float) -> dict:
        """Graceful-degradation report: how stale the host mirrors are
        (`last_retire_age_s`), how far each feed consumer has fallen
        behind the fan-out (`feed_depth`, items per (g, p) subscription),
        and `stalled_groups` — groups holding live UNDECIDED instances
        older than `stall_after` that have also decided nothing for that
        long.  That is the signature of a group with no reachable
        majority (minority partition / too many dead peers): proposals
        sit armed forever, and without this report the only symptom is
        clerks timing out.  Groups that are merely busy keep deciding
        (fresh `_g_last_decided`), and freshly-proposed work is younger
        than the threshold — neither is reported."""
        now = time.monotonic()
        live = self._slot_seq >= 0  # (G, I)
        undecided = live & ~(self.m_decided >= 0).any(axis=2)
        g_undec = undecided.any(axis=1)  # (G,)
        oldest = np.where(undecided, self._slot_alloc_t, np.inf).min(axis=1)
        oldest_age = np.where(g_undec, now - oldest, 0.0)
        decided_age = now - self._g_last_decided
        stalled = np.nonzero(g_undec & (oldest_age > stall_after)
                             & (decided_age > stall_after))[0]
        feed_depth: dict[str, int] = {}
        for (g, p), lst in self._subs.items():
            d = max((sub.depth() for sub in lst), default=0)
            if d:
                feed_depth[f"{g}:{p}"] = d
        # kernelscope stall diagnosis: recent protocol events (the two
        # fold-side window buckets — up to ~2×TPU6824_PROTO_WINDOW of
        # history), so a stalled group's report SAYS WHY it is stalled
        # (quorum failures climbing vs. no proposals arriving) instead
        # of just naming it.  Pure read: stale buckets (no fold for two
        # windows = the clock is not advancing) read as an all-zero
        # delta, which IS the "no proposals arriving" diagnosis.
        if now - self._proto_bucket_t > 2 * self._proto_window:
            delta = np.zeros_like(self._proto)
        else:
            delta = self._proto_bucket_cur + self._proto_bucket_prev
        diagnosis = {str(int(g)): self._diagnose_stall(delta[int(g)])
                     for g in stalled}
        return {
            "stall_diagnosis": diagnosis,
            "last_retire_age_s": round(now - self._last_retire_t, 6),
            "stall_after_s": stall_after,
            "stalled_groups": [int(g) for g in stalled],
            "oldest_undecided_age_s": round(float(oldest_age.max()), 6)
            if g_undec.any() else 0.0,
            "feed_depth": feed_depth,
            "feed_depth_max": max(feed_depth.values(), default=0),
            # Daemon-thread deaths (and survived keep-driving failures)
            # recorded through tpu6824.utils.crashsink: process-global —
            # a crashed kvpaxos driver or ticker shows up here even
            # though the thread belongs to a service, because this stats
            # call is the harness's one health window.
            "thread_crashes": crashsink.summary(),
            # Durability/recovery progress (durafault): restore() stamps
            # restored_from/recovery_time_s/decided_at_restore; an
            # attached continuous checkpointer keeps snapshot_seq/
            # snapshot_age_s/snapshot_bytes/truncated_horizon/
            # snapshots_written fresh.  {} = no durability story yet.
            "recovery": dict(self._recovery),
        }

    def ndecided(self, g: int, seq: int) -> int:
        """Test helper mirroring paxos/test_test.go:32-49: asserts agreement
        and returns how many peers have decided `seq`."""
        with self._lock:
            slot = self._seq2slot[g].get(seq)
            if slot is None:
                return 0
            d = self.m_decided[g, slot]
        vals = d[d >= 0]
        if len(vals):
            assert (vals == vals[0]).all(), f"seq {seq}: peers disagree: {d}"
        return int((d >= 0).sum())
