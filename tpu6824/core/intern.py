"""Host-side value interning.

The device agrees on int32 value *ids*; payloads (arbitrary picklable Python
values — the reference gob-encodes interface{} values the same way,
`paxos/rpc.go:44-84`) live in this refcounted host store.  When the Done/Min
window GC recycles an instance slot, its payload references are dropped — the
moral equivalent of `doMemShrink` freeing forgotten instances
(`paxos/paxos.go:362-378`) and the property the reference's TestForgetMem
asserts (`paxos/test_test.go:371-454`)."""

from __future__ import annotations

import pickle
import threading


class Intern:
    def __init__(self):
        self._lock = threading.Lock()
        self._by_key: dict[bytes, int] = {}
        self._vals: list = []
        self._keys: list = []
        self._refs: list[int] = []
        self._free: list[int] = []

    def put(self, value) -> int:
        """Intern `value`, increment its refcount, return its id."""
        key = pickle.dumps(value, protocol=4)
        with self._lock:
            vid = self._by_key.get(key)
            if vid is None:
                if self._free:
                    vid = self._free.pop()
                    self._vals[vid] = value
                    self._keys[vid] = key
                    self._refs[vid] = 0
                else:
                    vid = len(self._vals)
                    self._vals.append(value)
                    self._keys.append(key)
                    self._refs.append(0)
                self._by_key[key] = vid
            self._refs[vid] += 1
            return vid

    def get(self, vid: int):
        return self._vals[vid]

    def incref(self, vid: int):
        with self._lock:
            self._refs[vid] += 1

    def decref(self, vid: int):
        with self._lock:
            self._refs[vid] -= 1
            if self._refs[vid] <= 0:
                del self._by_key[self._keys[vid]]
                self._vals[vid] = None
                self._keys[vid] = None
                self._free.append(vid)

    @property
    def nlive(self) -> int:
        with self._lock:
            return len(self._vals) - len(self._free)

    def approx_bytes(self) -> int:
        """Rough payload footprint — enough for memory-reclamation tests."""
        with self._lock:
            return sum(len(k) for k in self._keys if k is not None)
