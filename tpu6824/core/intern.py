"""Host-side value interning.

The device agrees on int32 value *ids*; payloads (arbitrary picklable Python
values — the reference gob-encodes interface{} values the same way,
`paxos/rpc.go:44-84`) live in this refcounted host store.  When the Done/Min
window GC recycles an instance slot, its payload references are dropped — the
moral equivalent of `doMemShrink` freeing forgotten instances
(`paxos/paxos.go:362-378`) and the property the reference's TestForgetMem
asserts (`paxos/test_test.go:371-454`).

Two backends with one API: the native C++ store (`native/intern.cpp` — dedup
index, refcounts, free-list and byte accounting under a C++ mutex; Python
keeps only an id→value mirror for O(1) `get` without re-serialization), and
a pure-Python fallback when no toolchain is available.  `Intern()` picks.
"""

from __future__ import annotations

import ctypes
import pickle
import threading


class PyIntern:
    """Pure-Python reference implementation (and toolchain-less fallback)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._by_key: dict[bytes, int] = {}
        self._vals: list = []
        self._keys: list = []
        self._refs: list[int] = []
        self._free: list[int] = []
        # Decode counter: how many times get() resolved an id to a payload.
        # The decided-delta feed's contract is ONE decode per (group, seq)
        # regardless of replica count — tests assert it through this (a
        # plain int; += under the GIL is adequate for test accounting).
        self.gets = 0

    def put(self, value) -> int:
        """Intern `value`, increment its refcount, return its id."""
        key = pickle.dumps(value, protocol=4)
        with self._lock:
            vid = self._by_key.get(key)
            if vid is None:
                if self._free:
                    vid = self._free.pop()
                    self._vals[vid] = value
                    self._keys[vid] = key
                    self._refs[vid] = 0
                else:
                    vid = len(self._vals)
                    self._vals.append(value)
                    self._keys.append(key)
                    self._refs.append(0)
                self._by_key[key] = vid
            self._refs[vid] += 1
            return vid

    def get(self, vid: int):
        self.gets += 1
        return self._vals[vid]

    def incref(self, vid: int):
        with self._lock:
            self._refs[vid] += 1

    def decref(self, vid: int):
        with self._lock:
            self._refs[vid] -= 1
            if self._refs[vid] <= 0:
                del self._by_key[self._keys[vid]]
                self._vals[vid] = None
                self._keys[vid] = None
                self._free.append(vid)

    @property
    def nlive(self) -> int:
        with self._lock:
            return len(self._vals) - len(self._free)

    def approx_bytes(self) -> int:
        """Rough payload footprint — enough for memory-reclamation tests."""
        with self._lock:
            return sum(len(k) for k in self._keys if k is not None)


def _load_native():
    import os

    from tpu6824.native import build

    lib = build.load(
        "libintern6824.so",
        os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                     "native", "intern.cpp"),
        sanitize=os.environ.get("TPU6824_NATIVE_SANITIZE") or None,
    )
    if lib is None or getattr(lib, "_intern_bound", False):
        return lib
    lib.intern_new.restype = ctypes.c_void_p
    lib.intern_destroy.argtypes = [ctypes.c_void_p]
    lib.intern_put.restype = ctypes.c_int32
    lib.intern_put.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                               ctypes.c_int64, ctypes.POINTER(ctypes.c_int32)]
    lib.intern_incref.argtypes = [ctypes.c_void_p, ctypes.c_int32]
    lib.intern_decref.restype = ctypes.c_int32
    lib.intern_decref.argtypes = [ctypes.c_void_p, ctypes.c_int32]
    lib.intern_get_bytes.restype = ctypes.c_int64
    lib.intern_get_bytes.argtypes = [ctypes.c_void_p, ctypes.c_int32,
                                     ctypes.c_char_p, ctypes.c_int64]
    lib.intern_nlive.restype = ctypes.c_int64
    lib.intern_nlive.argtypes = [ctypes.c_void_p]
    lib.intern_bytes.restype = ctypes.c_int64
    lib.intern_bytes.argtypes = [ctypes.c_void_p]
    lib.intern_refcount.restype = ctypes.c_int64
    lib.intern_refcount.argtypes = [ctypes.c_void_p, ctypes.c_int32]
    lib._intern_bound = True
    return lib


class NativeIntern:
    """C++-backed store: serialization stays in Python (pickle), bookkeeping
    (dedup/refcount/free-list/bytes) lives in native code."""

    def __init__(self, lib):
        self._lib = lib
        self._h = lib.intern_new()
        self._mu = threading.Lock()
        self._vals: dict[int, object] = {}  # id → live value mirror
        self.gets = 0  # decode counter (see PyIntern.gets)

    def __del__(self):
        h, self._h = getattr(self, "_h", None), None
        if h:
            self._lib.intern_destroy(h)

    def put(self, value) -> int:
        key = pickle.dumps(value, protocol=4)
        is_new = ctypes.c_int32(0)
        # The mirror update must be atomic with the native call: a decref
        # freeing this vid (or a racing put reusing a freed vid) between the
        # two would desync id↔value.
        with self._mu:
            vid = self._lib.intern_put(self._h, key, len(key),
                                       ctypes.byref(is_new))
            if is_new.value:
                self._vals[vid] = value
        return vid

    def get(self, vid: int):
        with self._mu:
            self.gets += 1
            return self._vals[vid]

    def incref(self, vid: int):
        self._lib.intern_incref(self._h, vid)

    def decref(self, vid: int):
        with self._mu:
            if self._lib.intern_decref(self._h, vid):
                self._vals.pop(vid, None)

    def refcount(self, vid: int) -> int:
        return int(self._lib.intern_refcount(self._h, vid))

    def get_bytes(self, vid: int) -> "bytes | None":
        """The id-LOOKUP surface (ISSUE 11): recover the serialized
        payload bytes from an id alone, straight from the C++ store —
        None for a freed id.  The native-ingest path uses the same core
        call to materialize key/value strings lazily."""
        cap = 4096
        while True:
            buf = ctypes.create_string_buffer(cap)
            n = int(self._lib.intern_get_bytes(self._h, vid, buf, cap))
            if n < 0:
                return None
            if n <= cap:
                return buf.raw[:n]
            cap = n

    @property
    def nlive(self) -> int:
        return int(self._lib.intern_nlive(self._h))

    def approx_bytes(self) -> int:
        return int(self._lib.intern_bytes(self._h))


def Intern():
    """Build the native store when the toolchain allows, else pure Python."""
    lib = _load_native()
    return NativeIntern(lib) if lib is not None else PyIntern()
