"""Device-resident columnar KV apply kernel (ISSUE 16, devapply).

The hot kvpaxos state machine — get/put/append over interned ids — as a
pure function of fixed-shape device arrays, so the decided path applies
a whole drain in ONE jitted device step instead of a per-op host dict
walk under the server mutex.

State layout (all int32; ids are dense host-assigned intern indices, so
int32 is exact and x64 is never needed):

  - ``tbl_kid[S+1]``  open-addressed key table: slot → key id, -1 empty.
    S is a power of two (``TPU6824_DEVAPPLY_SLOTS``); slot S is a guard
    row that absorbs predicated no-op scatters so the step stays
    branch-free.
  - ``tbl_node[S+1]`` slot → chain node id of the key's current value.
  - ``chain_vid[C+1]`` / ``chain_prev[C+1]`` append chains: node →
    (value id, previous node).  A put starts a fresh chain (prev = -1);
    an append links a new node onto the key's current one.  Values stay
    interned on the host — the device never sees bytes, only ids — and
    a chain is resolved to a string at readback (services/devapply.py),
    once, memoized.  Node C is the guard row.
  - ``n_chain``      bump cursor: next free chain node.

The step is FULLY VECTORIZED — one gather plus three masked scatters
over op columns padded to a `core.jitshape` bucket, no scan and no
probe loop.  The sequential parts of the state machine are integer
bookkeeping the host already does for free while interning: slot
assignment (open-addressed probing against the host's shadow of
``tbl_kid`` — the engine owns collision handling), chain-node
allocation (writes take consecutive nodes, so node ids are known at
column-build time), and same-drain read-after-write (the predecessor
node of an op whose key was written earlier in the drain is a
host-known int).  What the device contributes is the O(batch) state
update against O(store) persistent arrays and the pre-node gather for
keys LAST written in some earlier drain — the actual state residency.
A first-generation kernel did the probing and ordering on-device with
``lax.scan`` + ``while_loop``; at 512-op buckets the sequential scan
cost ~16µs/op on CPU and would serialize just as badly on a real
accelerator — scatter/gather is the shape this machine is fast at.

Column contract: ONE packed ``(8, bucket)`` int32 matrix per step — a
single host→device transfer per chunk (per-column transfers cost 2×
the step itself on the CPU backend).  Rows, with their pad fills:

  - ``C_KIND``  op kind (K_NOP pad fill — its lane reads back -1).
  - ``C_SLOT``  the key's table slot (host-assigned; S for pads).
  - ``C_KID``   key id (for the table scatter; 0 pad).
  - ``C_VID``   value id for writes, 0 otherwise.
  - ``C_NODE``  absolute chain node for writes, -1 for gets/pads.
  - ``C_PREV``  absolute predecessor node when the key was written
    earlier in this drain, -1 → gather ``tbl_node[slot]`` instead.
  - ``C_TMASK`` nonzero on the op that is its key's LAST write in this
    batch — only that op scatters into the table, so duplicate slot
    indices never race (guard-row duplicates are junk-writes to a row
    nothing reads).
  - ``C_NC``    column 0 carries the bump cursor after this batch
    (host-known — writes take consecutive nodes).

The step returns, per op, the key's chain node BEFORE the op — which is
the get result, and the append's prev link — so ONE readback per drain
serves both reply resolution and the host chain shadow.

Jit/shard-ready by construction: ``apply_step`` is a pure state→state
function of one group's arrays with no host callbacks, so ROADMAP
item 1's ``shard_map`` over the ``'g'`` mesh axis composes by stacking
per-group states on a leading axis (``apply_step_groups`` is exactly
that ``vmap``); nothing in the kernel closes over host state.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

# Op kind codes for the device columns.  K_NOP is the pad fill: it
# neither gathers usefully nor writes (its output is masked to -1 and
# discarded by the host, which only reads back the first n live lanes).
K_NOP, K_GET, K_PUT, K_APPEND = 0, 1, 2, 3

# Rows of the packed op-column matrix.
C_KIND, C_SLOT, C_KID, C_VID, C_NODE, C_PREV, C_TMASK, C_NC = range(8)
N_COLS = 8

# Per-row pad fill, as a column vector: `fills(S)[:, None]` broadcast
# over the pad region restores a reused host buffer in one store.
def col_fills(slots: int) -> np.ndarray:
    f = np.zeros((N_COLS, 1), np.int32)
    f[C_KIND, 0] = K_NOP
    f[C_SLOT, 0] = slots
    f[C_NODE, 0] = -1
    f[C_PREV, 0] = -1
    return f

# Fibonacci-hash multiplier (0x9E3779B1) for the host-side slot probe.
# Slot assignment lives entirely on the host (`host_insert` against the
# engine's shadow of tbl_kid); the device consumes assigned slots.  The
# int32 form is DERIVED from the one constant — a hand-typed twin once
# differed by 8 and sent every host-built table's probes to the wrong
# slots (kept as a guard for any future device-side probe).
_MIX = 0x9E3779B1
_MIX_I32 = np.uint32(_MIX).astype(np.int32)


class DevKVState(NamedTuple):
    """One group's device-resident KV table (a jax pytree)."""

    tbl_kid: jax.Array
    tbl_node: jax.Array
    chain_vid: jax.Array
    chain_prev: jax.Array
    n_chain: jax.Array  # int32 scalar


def make_state(slots: int, chain: int) -> DevKVState:
    """Fresh empty state; `slots` must be a power of two."""
    if slots & (slots - 1):
        raise ValueError(f"devapply slots must be a power of two: {slots}")
    return DevKVState(
        tbl_kid=jnp.full(slots + 1, -1, jnp.int32),
        tbl_node=jnp.full(slots + 1, -1, jnp.int32),
        chain_vid=jnp.zeros(chain + 1, jnp.int32),
        chain_prev=jnp.full(chain + 1, -1, jnp.int32),
        n_chain=jnp.int32(0),
    )


def host_slot_iter(kid: int, slots: int):
    """The open-addressed probe sequence for `kid` (Fibonacci hash,
    linear step).  This is THE slot-assignment authority: the engine
    probes its host shadow of ``tbl_kid`` with it and hands the device
    resolved slots in the op columns."""
    mask = slots - 1
    h = ((kid ^ (kid >> 16)) * _MIX) & 0xFFFFFFFF
    s = h & mask
    for _ in range(slots):
        yield s
        s = (s + 1) & mask


def host_insert(tbl_kid: np.ndarray, slots: int, kid: int) -> int:
    """Insert (or find) `kid` in a host numpy table; returns the slot."""
    for s in host_slot_iter(kid, slots):
        k = tbl_kid[s]
        if k == kid or k == -1:
            tbl_kid[s] = kid
            return s
    raise RuntimeError("devapply host table full (rebase threshold bug)")


def _apply_cols(state: DevKVState, cols):
    """One batched apply step over a packed (8, bucket) op matrix:
    gather pre-nodes, scatter the chain and table updates.  Returns
    (new state, per-op pre-node column)."""
    kinds, slots, kids = cols[C_KIND], cols[C_SLOT], cols[C_KID]
    vids, nodes, prevs = cols[C_VID], cols[C_NODE], cols[C_PREV]
    tmask = cols[C_TMASK]
    new_nc = cols[C_NC, 0]
    neg1 = jnp.int32(-1)
    guard_c = jnp.int32(state.chain_vid.shape[0] - 1)
    guard_s = jnp.int32(state.tbl_kid.shape[0] - 1)
    # Pre-node per op: host-known for same-drain read-after-write,
    # gathered from the table otherwise.  Pads gather the guard row;
    # masked to -1 so the readback column is clean end to end.
    pre = jnp.where(prevs >= 0, prevs, state.tbl_node[slots])
    pre = jnp.where(kinds == K_NOP, neg1, pre)
    # Chain scatter: every write owns a distinct pre-assigned node, so
    # indices never collide; non-writes land on the guard row.
    iswrite = nodes >= 0
    cidx = jnp.where(iswrite, nodes, guard_c)
    chain_vid = state.chain_vid.at[cidx].set(
        jnp.where(iswrite, vids, jnp.int32(0)))
    chain_prev = state.chain_prev.at[cidx].set(
        jnp.where(iswrite & (kinds == K_APPEND), pre, neg1))
    # Table scatter: only each key's last write in the batch (tmask)
    # touches its slot — live indices are unique by construction.
    live = tmask != 0
    tslot = jnp.where(live, slots, guard_s)
    tbl_kid = state.tbl_kid.at[tslot].set(jnp.where(live, kids, neg1))
    tbl_node = state.tbl_node.at[tslot].set(jnp.where(live, nodes, neg1))
    return (DevKVState(tbl_kid, tbl_node, chain_vid, chain_prev,
                       jnp.asarray(new_nc, jnp.int32)), pre)


# The per-drain entry point: one compiled executable per (S, C, bucket)
# triple — S and C are fixed per process by env, buckets come from the
# finite jitshape ladder, so the signature set is finite (jitguard
# zero-steady-state-recompile contract).
#
# The state is DONATED: scatters update the persistent arrays in place
# instead of copying ~1.3MB of table+chain per step (the XLA functional
# default).  Callers must treat the passed-in state as consumed and
# chain the returned one; anything that must outlive the next step
# (the snapshot cut) copies out first.
apply_step = jax.jit(_apply_cols, donate_argnums=0)

# shard_map composition hook (ROADMAP item 1): per-group states stacked
# on a leading 'g' axis apply in one collective-free batched step.
apply_step_groups = jax.jit(jax.vmap(_apply_cols), donate_argnums=0)
