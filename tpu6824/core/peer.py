"""PaxosPeer — the per-peer view of a fabric group, with the reference's
public Paxos contract: Make/Start/Status/Done/Min/Max
(`paxos/paxos.go:13-21`)."""

from __future__ import annotations

import enum

from tpu6824.core.fabric import PaxosFabric


class Fate(enum.Enum):
    # paxos/paxos.go Fate constants: Decided / Pending / Forgotten.
    DECIDED = 1
    PENDING = 2
    FORGOTTEN = 3


class PaxosPeer:
    """Handle for peer `me` of group `g` on a shared fabric.

    The reference's `Make(peers, me, rpcs)` (paxos/paxos.go:488-557) boots a
    socket listener per peer; here all peers of all groups share one device
    fabric, and a handle is just (group, index) coordinates into it."""

    def __init__(self, fabric: PaxosFabric, g: int, me: int):
        self.fabric = fabric
        self.g = g
        self.me = me

    def start(self, seq: int, value) -> None:
        """Async: begin agreement on instance seq (paxos/paxos.go:99-109)."""
        self.fabric.start(self.g, self.me, seq, value)

    def status(self, seq: int) -> tuple[Fate, object]:
        """Local-only read (paxos/paxos.go:434-447)."""
        return self.fabric.status(self.g, self.me, seq)

    # Batched extensions (used by group-commit RSM drivers when present;
    # every consumer falls back to the scalar contract otherwise):

    def start_many(self, pairs) -> None:
        """One lock acquisition for a block of (seq, value) proposals;
        WindowFullError carries the resume index (fabric.start_many)."""
        g, me = self.g, self.me
        self.fabric.start_many([(g, me, s, v) for s, v in pairs])

    def status_many(self, seqs) -> list:
        g, me = self.g, self.me
        return self.fabric.status_many([(g, me, s) for s in seqs])

    def drain_decided(self, lo: int, max_n: int = 256):
        """(values, next_seq, forgotten) for the decided prefix at `lo` —
        one vectorized fabric pass (see PaxosFabric.drain_decided)."""
        return self.fabric.drain_decided(self.g, self.me, lo, max_n)

    def subscribe_decided(self, wake=None):
        """Subscribe this peer to the fabric's decided-delta feed
        (PaxosFabric.subscribe_decided), or None when the backend has no
        feed — a `remote_fabric` Proxy synthesizes ANY method name, so
        feature-detect by type, not getattr (callers fall back to
        drain_decided on None)."""
        if not isinstance(self.fabric, PaxosFabric):
            return None
        return self.fabric.subscribe_decided(self.g, self.me, wake=wake)

    @property
    def profiler(self):
        """The fabric's PhaseProfiler (services record their apply/notify
        legs into it so stats() shows the whole decided pipeline); None on
        non-fabric backends — same Proxy caveat as subscribe_decided."""
        if not isinstance(self.fabric, PaxosFabric):
            return None
        return self.fabric.profiler

    def wait_progress(self, timeout: float = 0.05) -> None:
        """Block until the fabric clock advances (or timeout) — the batched
        analog of the reference's poll-with-backoff sleep
        (kvpaxos/server.go:73-77).  Positional args only: the fabric may
        be a remote_fabric Proxy, whose RPC surface takes no kwargs."""
        self.fabric.wait_steps(1, timeout)

    def done(self, seq: int) -> None:
        self.fabric.done(self.g, self.me, seq)

    def done_deferred(self, seq: int) -> None:
        """Lock-free Done (fabric.done_deferred): folded by the clock at
        its next dispatch staging — the hot RSM drivers' variant, so a
        driver never convoys behind a retire fold holding the fabric
        lock.  Falls back to the locked path off-fabric."""
        if not isinstance(self.fabric, PaxosFabric):
            self.fabric.done(self.g, self.me, seq)
            return
        self.fabric.done_deferred(self.g, self.me, seq)

    def min(self) -> int:
        return self.fabric.peer_min(self.g, self.me)

    def max(self) -> int:
        return self.fabric.peer_max(self.g, self.me)

    def kill(self) -> None:
        self.fabric.kill(self.g, self.me)

    @property
    def dead(self) -> bool:
        return self.fabric.is_dead(self.g, self.me)


def make_group(fabric: PaxosFabric, g: int = 0) -> list[PaxosPeer]:
    """All P peer handles of group g — the analog of calling paxos.Make once
    per server process."""
    return [PaxosPeer(fabric, g, p) for p in range(fabric.P)]
