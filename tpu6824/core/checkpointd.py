"""checkpointd — continuous fabric checkpointing + crash-consistent recovery.

The one-shot `PaxosFabric.checkpoint()` (tests/test_checkpoint.py) needs
a stopped clock and an operator who remembers to call it; durafault makes
durability CONTINUOUS: a crashsink-guarded daemon snapshots the whole
`(G, I, P)` consensus universe every `interval` seconds into a directory
of sequence-numbered, checksum-framed files, prunes old ones, and a
reboot path (`recover_newest`) restores from the newest snapshot that
passes its frame — discarding torn/truncated ones instead of serving
garbage as decided state.

Cost model: the fabric clock pauses only for the state COPY
(`snapshot_blob()` — numpy copies of the device mirrors + queue
snapshots under the lock); pickling and the durafs disk write run with
the clock already restarted, so live traffic waits out milliseconds, not
the IO.  Nothing here touches the step path — the daemon piggybacks on
no dispatch and adds no device readback beyond the snapshot's own mirror
copy (tpusan `readback-in-step` stays clean: this module is not in the
step scope, and the warmed step jits are untouched — asserted by the
jitguard leg in tests/test_durafault.py).

Log truncation rides the existing Done()/Min() window GC: the snapshot
records the fabric's done-view horizon (`truncated_horizon` — every
instance below it may be forgotten everywhere), so a recovered service
replays only the un-truncated suffix above its own applied watermark and
pulls anything older from peers (services/diskv.py's FORGOTTEN path).

Metrics (tpuscope registry): `fabric.recovery.snapshot_age_s`,
`.snapshot_bytes`, `.snapshot_seq`, `.snapshots_written`,
`.snapshots_discarded`, `.truncated_horizon` — plus
`fabric.recovery.recovery_time_s` stamped by `PaxosFabric.restore`.  The
same numbers land in `stats()["health"]["recovery"]` via
`set_recovery_info`, and the bench recovery leg records recovery-time
p50/p95 gated by benchdiff.
"""

from __future__ import annotations

import os
import pickle
import re
import threading
import time

import numpy as np

from tpu6824.core.fabric import (
    CorruptCheckpointError, PaxosFabric, frame_checkpoint,
)
from tpu6824.obs import metrics as obs_metrics
from tpu6824.utils import crashsink, durafs

_M_AGE = obs_metrics.gauge("fabric.recovery.snapshot_age_s")
_M_BYTES = obs_metrics.gauge("fabric.recovery.snapshot_bytes")
_M_SEQ = obs_metrics.gauge("fabric.recovery.snapshot_seq")
_M_WRITTEN = obs_metrics.gauge("fabric.recovery.snapshots_written")
_M_DISCARDED = obs_metrics.gauge("fabric.recovery.snapshots_discarded")
_M_HORIZON = obs_metrics.gauge("fabric.recovery.truncated_horizon")

#: Snapshot file naming: monotone sequence numbers, so "newest" is an
#: ordering on names, never on mtimes (which a restore/copy can skew).
CKPT_RE = re.compile(r"^ckpt-(\d{8})\.bin$")


class NoValidCheckpointError(RuntimeError):
    """Recovery found no snapshot that passes its checksum frame.  The
    `report` attribute carries what was tried and why each was
    discarded."""

    def __init__(self, msg: str, report: dict):
        super().__init__(msg)
        self.report = report


def list_checkpoints(ckpt_dir: str) -> list[tuple[int, str]]:
    """(seq, path) of every snapshot file, newest first."""
    try:
        names = os.listdir(ckpt_dir)
    except FileNotFoundError:
        return []
    out = []
    for n in names:
        m = CKPT_RE.match(n)
        if m:
            out.append((int(m.group(1)), os.path.join(ckpt_dir, n)))
    return sorted(out, reverse=True)


def recover_newest(ckpt_dir: str, **kw):
    """Boot a fabric from the newest VALID snapshot in `ckpt_dir`.

    Scans newest-first; a file that fails its frame (torn write,
    truncation, bit rot) or its unpickle/restore is DISCARDED — recorded
    in the report, counted in `fabric.recovery.snapshots_discarded` —
    and the scan falls back to the next-older snapshot.  This is the
    acceptance property durafault exists for: recovery must refuse a
    torn snapshot, never serve from it.

    Returns `(fabric, report)`; raises NoValidCheckpointError when
    nothing in the directory restores.  `kw` passes through to
    `PaxosFabric.restore` (auto_step=...)."""
    report: dict = {"dir": ckpt_dir, "discarded": [], "restored_from": None}
    cands = list_checkpoints(ckpt_dir)
    for seq, path in cands:
        try:
            fab = PaxosFabric.restore(path, **kw)
        except (CorruptCheckpointError, OSError, pickle.UnpicklingError,
                EOFError, KeyError, ValueError) as e:
            report["discarded"].append(
                {"path": os.path.basename(path), "error": repr(e)[:200]})
            continue
        report["restored_from"] = os.path.basename(path)
        report["snapshot_seq"] = seq
        if report["discarded"]:
            _M_DISCARDED.set(len(report["discarded"]))
        fab.set_recovery_info(
            snapshot_seq=seq,
            discarded=[d["path"] for d in report["discarded"]])
        return fab, report
    raise NoValidCheckpointError(
        f"no valid checkpoint under {ckpt_dir} "
        f"({len(cands)} candidate(s), all discarded)", report)


class ContinuousCheckpointer:
    """Crashsink-guarded snapshot daemon over a live fabric.

    Each cycle: pause the clock just long enough to copy the state
    (`snapshot_blob`), restart it, then pickle + checksum-frame + write
    via the durafs discipline to `ckpt-<seq>.bin`, prune to the newest
    `keep` files, and refresh the recovery gauges + the fabric's
    health["recovery"] block.  A cycle that loses a clock race (another
    thread pausing/starting the clock — the nemesis clock_pause action)
    or hits a disk fault records the failure and tries again next
    interval: durability degrades to a staler snapshot, never to a dead
    daemon.

    Clock ownership: the snapshot uses `fabric.pause_clock()/
    resume_clock()` — a borrow, not a stop.  Any concurrent
    `stop_clock` (a nemesis clock_pause, a test teardown, a harness
    shutdown) casts a stop VOTE that makes the daemon's deferred resume
    a no-op, so an external stop is never silently undone by a snapshot
    cycle.  The only residual interleaving effect is timing noise (a
    snapshot copy can extend how long a concurrent pause keeps the
    clock stopped), so seeded soaks that want exact pause durations
    still exclude `clock_pause`, as the durafault soak does."""

    def __init__(self, fabric: PaxosFabric, ckpt_dir: str,
                 interval: float = 0.5, keep: int = 3):
        self.fabric = fabric
        self.dir = ckpt_dir
        self.interval = interval
        self.keep = max(1, keep)
        os.makedirs(ckpt_dir, exist_ok=True)
        self._seq = max((s for s, _ in list_checkpoints(ckpt_dir)),
                        default=0)
        self.written = 0
        self.failed = 0
        self._last_write_t = time.monotonic()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # ------------------------------------------------------------ daemon

    def start(self) -> "ContinuousCheckpointer":
        self._thread = threading.Thread(
            target=crashsink.guarded(self._loop, "fabric-checkpointd"),
            daemon=True)
        self._thread.start()
        return self

    def stop(self, final: bool = True) -> None:
        """Stop the daemon; `final=True` writes one last snapshot after
        the loop exits (the fabricd SIGTERM path — nothing decided after
        the last interval tick may be lost to shutdown)."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join()
            self._thread = None
        if final:
            try:
                self.snapshot_once()
            except (OSError, RuntimeError) as e:
                crashsink.record("fabric-checkpointd-final", e, fatal=False)

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            _M_AGE.set(round(time.monotonic() - self._last_write_t, 6))
            try:
                self.snapshot_once()
            except (OSError, RuntimeError) as e:
                # Disk fault (durafs injection / real ENOSPC) or a clock
                # race: skip the cycle, surface it, keep the daemon.
                self.failed += 1
                crashsink.record("fabric-checkpointd", e, fatal=False)

    # --------------------------------------------------------- snapshots

    def snapshot_once(self) -> str:
        """One full-universe snapshot; returns the written path."""
        fab = self.fabric
        # pause/resume (not stop/start): if any OTHER caller stop_clock()s
        # while the snapshot copies, the resume is skipped — that caller
        # owns the stopped state and the daemon must not undo it.
        was_running, token = fab.pause_clock()
        try:
            blob = fab.snapshot_blob()
        finally:
            fab.resume_clock(was_running, token)
        # Serialization + IO off the clock AND off the fabric lock.
        payload = pickle.dumps(blob, protocol=pickle.HIGHEST_PROTOCOL)
        framed = frame_checkpoint(payload)
        self._seq += 1
        path = os.path.join(self.dir, f"ckpt-{self._seq:08d}.bin")
        durafs.atomic_write(path, framed)
        self.written += 1
        self._last_write_t = time.monotonic()
        # Done()/Min() truncation horizon at snapshot time: everything
        # below it may already be forgotten fabric-wide, so recovery
        # replays only the suffix above it (peers donate the rest).
        horizon = int(np.asarray(blob["m_done_view"]).min()) + 1
        _M_AGE.set(0.0)
        _M_BYTES.set(len(framed))
        _M_SEQ.set(self._seq)
        _M_WRITTEN.set(self.written)
        _M_HORIZON.set(horizon)
        fab.set_recovery_info(
            snapshot_seq=self._seq, snapshot_bytes=len(framed),
            snapshot_t_monotonic=self._last_write_t,
            snapshots_written=self.written,
            snapshot_failures=self.failed,
            truncated_horizon=horizon)
        self._prune()
        return path

    def _prune(self) -> None:
        for _seq, path in list_checkpoints(self.dir)[self.keep:]:
            try:
                os.unlink(path)
            except OSError:
                continue
        # Torn-write debris (`ckpt-*.bin.<pid>.<tid>.tmp` from an
        # injected/real fault mid-snapshot): CKPT_RE never matches it,
        # so without this sweep a fault-heavy soak grows the checkpoint
        # dir without bound.  Safe: this daemon is the dir's only
        # writer, and its own in-flight tmp is already renamed by the
        # time prune runs.
        for name in os.listdir(self.dir):
            if name.endswith(".tmp"):
                try:
                    os.unlink(os.path.join(self.dir, name))
                except OSError:
                    continue
