"""fabdev — the fabric's device plane: mesh placement, sharded step
dispatch, and shard-local readback (ISSUE 17, meshfab).

`PaxosFabric` grew up single-device; its mesh support (PR 12) was a
handful of `if self._mesh is not None` branches threaded through the
host runtime.  This module is the split the ROADMAP licensed: every
decision about WHERE device state lives and WHICH compiled step runs is
made here, once, at construction — the fabric keeps the host-side
runtime (queues, mirrors, clock, feed) and calls the plane for
placement.

The plane owns three concerns:

  1. **Shape policy** — the live group count is ladder-padded
     (`jitshape.shard_groups`) to a per-shard rung × shard count, so an
     arbitrary service topology (7 shardkv groups + 1 master) rides any
     mesh with a FINITE set of compiled signatures; padding groups are
     idle lanes the host never starts.  A 1-shard mesh pads nothing —
     the degradation-to-single-device contract starts here.
  2. **Step selection + input placement** — the sharded step functions
     (jit + NamedSharding over the 'g'/'i'/'p' axes, psum-by-reduction
     on the peer axis) and the device_put shardings for every host→
     device operand (link/done/key/drop columns, the compact slot map).
     The identity-critical real path stays on the GSPMD form: jit with
     in_shardings is semantically the single-device program, so the
     decide stream is BIT-identical to an unsharded fabric with the
     same seed (asserted by tests/test_meshfab.py).
  3. **Placement map + shard-local readback** — which mesh shard owns
     which group (`shard_of`/`groups_of`), and `fetch_host`, which
     reassembles a sharded array on the host from its addressable
     shards directly: per-shard column pulls, no cross-device
     all-gather on the snapshot path.
"""

from __future__ import annotations

import numpy as np

import jax

from tpu6824.core.jitshape import shard_groups
from tpu6824.obs import metrics as obs_metrics

# meshfab topology gauges (module scope per the metric-unregistered
# rule): set at plane construction — the process-wide view of the live
# fabric's mesh shape, scraped by pulse alongside the fabric health
# gauges.
_M_SHARDS = obs_metrics.gauge("meshfab.shards")
_M_GROUPS_PER_SHARD = obs_metrics.gauge("meshfab.groups_per_shard")


class DevicePlane:
    """One fabric's device-placement authority (see module docstring).

    Attributes the fabric consumes:
      - ``G``            ladder-padded group count (== the requested
                         count on a 1-shard mesh);
      - ``step_fn`` / ``step_reliable`` / ``apply_starts`` — the
        compiled sharded entry points (``reliable_ok`` says whether the
        zero-drop specialization applies, i.e. the XLA path resolved);
      - ``sh_link/sh_done/sh_key/sh_drop`` — NamedShardings for the
        host-staged step operands.
    """

    def __init__(self, mesh, ngroups: int, ninstances: int, npeers: int,
                 kernel: str | None = None):
        from tpu6824.parallel.mesh import (
            sharded_apply_starts, sharded_step_auto, sharded_step_reliable,
            step_args_shardings,
        )

        self.mesh = mesh
        self.shards = int(mesh.shape["g"])
        # 'i'/'p' mesh axes must divide exactly — the window is a ring
        # the host walks by absolute index and the peer axis is the
        # quorum denominator; padding either would change protocol
        # semantics, not just waste lanes.  Only the group axis (pure
        # data parallelism) is pad-eligible.
        for ax, dim in (("i", ninstances), ("p", npeers)):
            if dim % mesh.shape[ax]:
                raise ValueError(
                    f"fabric {ax}-dim {dim} not divisible by mesh "
                    f"axis {ax}={mesh.shape[ax]}")
        self.G_live = int(ngroups)
        self.G = shard_groups(ngroups, self.shards)
        self.groups_per_shard = self.G // self.shards
        _M_SHARDS.set(self.shards)
        _M_GROUPS_PER_SHARD.set(self.groups_per_shard)

        self.step_fn, impl = sharded_step_auto(mesh, impl=kernel)
        self.impl = impl
        self.reliable_ok = impl == "xla"
        self.step_reliable = (sharded_step_reliable(mesh)
                              if self.reliable_ok else None)
        self.apply_starts = sharded_apply_starts(mesh)
        (self.sh_link, self.sh_done, self.sh_key,
         self.sh_drop, _) = step_args_shardings(mesh)

        from jax.sharding import NamedSharding, PartitionSpec

        self._sh_gi = NamedSharding(mesh, PartitionSpec("g", "i"))

    # ------------------------------------------------------- placement map

    def shard_of(self, g: int) -> int:
        """Mesh shard owning group `g` (contiguous block placement —
        the reshape/hybrid mesh orders 'g' coordinates by device, so a
        block of `groups_per_shard` consecutive groups shares one
        device column)."""
        return int(g) // self.groups_per_shard

    def groups_of(self, shard: int) -> range:
        """The contiguous group block owned by `shard` (includes any
        ladder-padding lanes at the tail of the last shards)."""
        per = self.groups_per_shard
        return range(shard * per, (shard + 1) * per)

    # --------------------------------------------------------- placement

    def place_state(self, state):
        from tpu6824.parallel.mesh import place_state

        return place_state(state, self.mesh)

    def put(self, kind: str, x):
        """Host step operand → its mesh placement.  A committed
        single-device array would conflict with the sharded step's
        in_shardings — every host-staged input flows through here."""
        sh = {"link": self.sh_link, "done": self.sh_done,
              "drop": self.sh_drop}[kind]
        return jax.device_put(np.asarray(x), sh)

    def put_key(self, sub):
        return jax.device_put(sub, self.sh_key)

    def place_slot_seq(self, ss):
        """The compact path's device slot→seq map, sharded (g, i)."""
        return jax.device_put(ss, self._sh_gi)

    # ---------------------------------------------------------- readback

    @staticmethod
    def fetch_host(x) -> np.ndarray:
        """Sharded device array → host ndarray by per-shard column
        pulls: each addressable shard's block is copied into its slice
        of the host buffer directly.  No XLA all-gather, no transient
        fully-replicated device copy — the snapshot path reads each
        owning shard's columns and nothing else."""
        out = np.empty(x.shape, x.dtype)
        for s in x.addressable_shards:
            out[s.index] = np.asarray(s.data)
        return out
