"""Fabric service — the device-owning runtime process, served over RPC.

This is the control-plane split of SURVEY §2.3 made concrete: exactly one
process owns the TPU arrays and the step clock (`PaxosFabric`); every other
process — replica daemons, clerks, the test harness — drives it through the
`Make/Start/Status/Done/Min/Max` contract over the L0 socket transport.  The
reference instead gives every server process its own Paxos peer and a socket
listener (`paxos/paxos.go:488-557`); here peers are (group, index) lanes of
one batched device kernel, so "a server process" holds coordinates, not state.

Wire surface = the fabric's public API plus the harness fault hooks (the
filesystem/socket surgery of `paxos/test_test.go` maps to `partition/deafen/
set_unreliable/kill/revive` on the serving side).
"""

from __future__ import annotations

from tpu6824.core.fabric import PaxosFabric
from tpu6824.rpc import Proxy, Server, connect

FABRIC_RPCS = [
    # paxos contract (per peer-lane)
    "start", "status", "done", "peer_min", "peer_max",
    # batched variants (one RPC for a whole step's worth of ops).
    # start_many is NOT atomic: a WindowFullError reply means the prefix
    # ops[:e.index] was applied and the rest dropped — resume the batch
    # from e.index (retry-from-0 is safe but re-queues the prefix; see
    # PaxosFabric.start_many).
    "start_many", "status_many", "done_many",
    # vectorized RSM drain (PaxosFabric.drain_decided — MUST stay in this
    # list: PaxosPeer exposes it unconditionally and the RPC Proxy
    # synthesizes any method name, so omitting it here would turn the
    # group-commit drive loop into an RPCError retry livelock)
    "drain_decided",
    # clock pacing for group-commit drivers (blocks server-side until the
    # next step or timeout; positional args — the Proxy takes no kwargs)
    "wait_steps",
    # shard binding (meshfab): which mesh shard owns group g.  Services
    # probe it with hasattr at attach — but the Proxy synthesizes ANY
    # method name, so omitting it here turns every remote-fabric service
    # attach into an RPCError, not a single-shard fallback.
    "shard_of",
    # harness / fault injection (set_pipeline_depth: live depth churn —
    # the nemesis engine treats pipeline depth as a fault dimension)
    "ndecided", "set_unreliable", "partition", "heal", "deafen",
    "set_link", "kill", "revive", "is_dead", "set_pipeline_depth",
    # introspection (stats carries the graceful-degradation health block
    # — last-retire age, feed queue depths, stalled-group detection with
    # kernelscope protocol diagnosis — plus stats()["protocol"], the
    # device-resident per-group consensus counters; metrics is the
    # process-global tpuscope registry snapshot — one JSON shape spanning
    # rpc/clerk/service/fabric counters; flight is the process-global
    # flight-recorder dump the kernelscope fleet collector merges into
    # one cross-process Perfetto timeline; pulse is the continuous
    # time-series snapshot — bounded rings of counter rates / gauges /
    # per-interval latency percentiles sampled by obs/pulse.py, the
    # surface `python -m tpu6824.obs.top` and the watchdog read — a
    # stable `enabled: False` shell when no pulse runs in the process;
    # opscope is the per-stage request-path latency waterfall
    # (obs/opscope.py, ISSUE 15) — always-on stage histograms + tail
    # exemplars, merged fleet-wide by the Collector, with the same
    # mixed-fleet rule: a pre-opscope member yields the disabled shell;
    # blackbox is the crash-surviving flight-data recorder's status
    # (obs/blackbox.py, ISSUE 20) — ring path / seal count / bytes
    # written, same mixed-fleet rule: a pre-blackbox member answering
    # "no such rpc" yields the stable disabled shell)
    "dims", "stats", "metrics", "flight", "pulse", "opscope", "blackbox",
]


def serve_fabric(fabric: PaxosFabric, addr: str, seed: int | None = None) -> Server:
    # `dims` lets remote processes size make_group()-style loops.
    fabric.dims = lambda: (fabric.G, fabric.I, fabric.P)
    return Server(addr, seed=seed).register_obj(fabric, FABRIC_RPCS).start()


def remote_fabric(addr: str, timeout: float = 30.0) -> Proxy:
    """A PaxosFabric-shaped handle over the wire; drop-in for PaxosPeer and
    the services (same method names, RPCError on transport failure)."""
    return connect(addr, timeout=timeout)
