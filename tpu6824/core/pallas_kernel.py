"""Pallas TPU kernel for the hot op: one fused Prepare→Accept→Decide round.

The XLA path (`tpu6824/core/kernel.py:paxos_step`) expresses the round as ~40
jnp ops over `(G, I, P, P)` intermediates and relies on XLA fusion.  This
module fuses the whole round into ONE Pallas kernel:

  - cells are laid out `(P, N)` with `N = G·I` on the lane axis, so every
    per-edge exchange is an elementwise VPU op over a `(1, C)` vector of
    cells; the tiny peer axis (P = 3..7) is statically unrolled;
  - each grid step loads a `C`-cell block of the 7 state arrays plus the 5
    per-edge delivery masks into VMEM, runs all three phases without touching
    HBM, and writes the 6 outputs — a single HBM round-trip per step versus
    the XLA path's chain of fused-but-separate kernels;
  - delivery masks (the reference harness's lossy network,
    `paxos/paxos.go:528-544`, as per-edge Bernoulli keeps) are generated
    host-side with EXACTLY the same `jax.random` splits as the XLA path, so
    both paths are bit-identical under the same key when drop probabilities
    are zero, and distributionally identical otherwise.

Semantics are those of `paxos_step` (see kernel.py's docstring for the
mapping to `paxos/paxos.go`); the only realization difference is that the
Done-piggyback (`paxos/rpc.go:74-80`) rides the heartbeat + prepare traffic
rather than all three phases' traffic — same information flow, fewer mask
materializations.

Select with `TPU6824_KERNEL=pallas` (see `tpu6824/config.py`); falls back to
interpret mode off-TPU so the CPU test suite can verify equivalence.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from tpu6824.core.kernel import NO_VAL, PaxosState, StepIO, _edge_masks

I32 = jnp.int32
LANES = 128  # TPU lane width; cell blocks are multiples of this


def _round_kernel(P: int,
                  np_ref, na_ref, va_ref, dec_ref, act_ref, propv_ref, ms_ref,
                  m1_ref, m2_ref, m3_ref, r1_ref, r2_ref,
                  np_out, na_out, va_out, dec_out, ms_out, msgs_out):
    """One consensus round for a (P, C) block of cells.

    All refs are (P, C) or (P, P, C) int32; masks are 0/1.  Every operand
    below is a (1, C) lane vector; loops over the peer axis are unrolled at
    trace time.
    """

    def row(ref, p):
        return ref[p:p + 1, :]

    def edge(ref, p, q):
        return ref[p, q:q + 1, :] != 0

    np_pre = [row(np_ref, p) for p in range(P)]
    na_pre = [row(na_ref, p) for p in range(P)]
    va_pre = [row(va_ref, p) for p in range(P)]
    dec_pre = [row(dec_ref, p) for p in range(P)]
    active = [row(act_ref, p) != 0 for p in range(P)]
    propv = [row(propv_ref, p) for p in range(P)]
    maxseen = [row(ms_ref, p) for p in range(P)]

    # n = k·P + p + 1: globally unique, > maxseen (kernel.py:137).
    n_prop = [(maxseen[p] // P + 1) * P + (p + 1) for p in range(P)]

    zero = jnp.zeros_like(np_pre[0])

    # ---- Phase 1: PREPARE --------------------------------------------------
    # Delivery: D1[p→q]; promise iff n_prop[p] > np_pre[q] (paxos.go:244-257).
    D1 = [[edge(m1_ref, p, q) & active[p] for q in range(P)] for p in range(P)]
    np_post1 = []
    for q in range(P):
        hi = np_pre[q]
        for p in range(P):
            hi = jnp.maximum(hi, jnp.where(D1[p][q], n_prop[p], 0))
        np_post1.append(hi)

    maj1, v1 = [], []
    for p in range(P):
        cnt = zero
        best_na = zero - 1
        va_best = propv[p]
        for q in range(P):
            grant = D1[p][q] & (n_prop[p] > np_pre[q])
            got = grant & edge(r1_ref, p, q)
            cnt = cnt + got.astype(I32)
            cand = jnp.where(got, na_pre[q], -1)
            upd = cand > best_na
            best_na = jnp.where(upd, cand, best_na)
            va_best = jnp.where(upd, va_pre[q], va_best)
        maj1.append(cnt * 2 > P)
        # Adopt highest accepted value among promisers (paxos.go:166-189).
        v1.append(jnp.where(best_na > 0, va_best, propv[p]))

    ms_new = []
    for p in range(P):
        hi = maxseen[p]
        for q in range(P):
            rep = D1[p][q] & edge(r1_ref, p, q)
            hi = jnp.maximum(hi, jnp.where(rep, np_post1[q], 0))
        ms_new.append(hi)

    # ---- Phase 2: ACCEPT ---------------------------------------------------
    # Accept iff n >= promised; one winner per acceptor per step — the
    # highest delivered n (per-step serialization rule, kernel.py:168-173).
    send2 = [active[p] & maj1[p] for p in range(P)]
    D2 = [[edge(m2_ref, p, q) & send2[p] for q in range(P)] for p in range(P)]
    ok2 = [[D2[p][q] & (n_prop[p] >= np_post1[q]) for q in range(P)]
           for p in range(P)]
    win_n = []
    for q in range(P):
        hi = zero
        for p in range(P):
            hi = jnp.maximum(hi, jnp.where(ok2[p][q], n_prop[p], 0))
        win_n.append(hi)
    win = [[ok2[p][q] & (n_prop[p] == win_n[q]) for q in range(P)]
           for p in range(P)]

    np_post2, na_new, va_new = [], [], []
    for q in range(P):
        any_acc = win_n[q] > 0
        np_post2.append(jnp.maximum(np_post1[q], win_n[q]))
        na_new.append(jnp.where(any_acc, win_n[q], na_pre[q]))
        va_win = zero
        for p in range(P):
            va_win = va_win + jnp.where(win[p][q], v1[p], 0)
        va_new.append(jnp.where(any_acc, va_win, va_pre[q]))

    maj2 = []
    for p in range(P):
        cnt = zero
        for q in range(P):
            cnt = cnt + (win[p][q] & edge(r2_ref, p, q)).astype(I32)
        maj2.append(cnt * 2 > P)
        hi = ms_new[p]
        for q in range(P):
            rep = D2[p][q] & edge(r2_ref, p, q)
            hi = jnp.maximum(hi, jnp.where(rep, np_post2[q], 0))
        ms_new[p] = hi

    # ---- Phase 3: DECIDE + gossip (kernel.py:185-195) ----------------------
    all_dec = dec_pre[0] >= 0
    for p in range(1, P):
        all_dec = all_dec & (dec_pre[p] >= 0)
    decider = [send2[p] & maj2[p] for p in range(P)]
    dv = [jnp.where(decider[p], v1[p], dec_pre[p]) for p in range(P)]
    send3 = [decider[p] | ((dec_pre[p] >= 0) & ~all_dec) for p in range(P)]
    D3 = [[edge(m3_ref, p, q) & send3[p] for q in range(P)] for p in range(P)]
    dec_new = []
    for q in range(P):
        inc = zero + NO_VAL
        for p in range(P):
            inc = jnp.maximum(inc, jnp.where(D3[p][q], dv[p], NO_VAL))
        dec_new.append(jnp.where(dec_pre[q] >= 0, dec_pre[q], inc))

    # Remote-message count per sender (self edges excluded) — RPC budget
    # analog (paxos/test_test.go:503-573).
    msgs = []
    for p in range(P):
        cnt = zero
        for q in range(P):
            if q == p:
                continue
            cnt = (cnt + D1[p][q].astype(I32) + D2[p][q].astype(I32)
                   + D3[p][q].astype(I32))
        msgs.append(cnt)

    np_out[...] = jnp.concatenate(np_post2, axis=0)
    na_out[...] = jnp.concatenate(na_new, axis=0)
    va_out[...] = jnp.concatenate(va_new, axis=0)
    dec_out[...] = jnp.concatenate(dec_new, axis=0)
    ms_out[...] = jnp.concatenate(ms_new, axis=0)
    msgs_out[...] = jnp.concatenate(msgs, axis=0)


def _to_lanes(a, P, N, Np, fill):
    """(G, I, P) → (P, Np) int32, cells on lanes, padded with `fill`."""
    a = jnp.moveaxis(a, 2, 0).reshape(P, N).astype(I32)
    if Np != N:
        a = jnp.pad(a, ((0, 0), (0, Np - N)), constant_values=fill)
    return a


def _mask_to_lanes(m, P, N, Np):
    """(G, I, P, P) bool → (P, P, Np) int32 [src, dst, cell]."""
    m = jnp.moveaxis(m.reshape(N, P, P), 0, 2).astype(I32)
    if Np != N:
        m = jnp.pad(m, ((0, 0), (0, 0), (0, Np - N)), constant_values=0)
    return m


def _from_lanes(a, G, I, P, N):
    return jnp.moveaxis(a[:, :N].reshape(P, G, I), 0, 2)


@functools.partial(jax.jit, static_argnames=("interpret",))
def paxos_step_pallas(
    state: PaxosState,
    link: jnp.ndarray,       # (G, P, P) bool
    done: jnp.ndarray,       # (G, P) i32
    key: jnp.ndarray,
    drop_req: jnp.ndarray,   # (G, P, P) f32
    drop_rep: jnp.ndarray,   # (G, P, P) f32
    interpret: bool = False,
) -> tuple[PaxosState, StepIO]:
    """Drop-in replacement for `paxos_step` with the round fused in Pallas."""
    G, I, P = state.np_.shape
    N = G * I
    eye = jnp.eye(P, dtype=bool)
    shape4 = (G, I, P, P)
    # Same splits as paxos_step (kernel.py:123) for bit-exact masks.
    k1, k2, k3, k1r, k2r, _k3r, khb = jax.random.split(key, 7)
    L = (link | eye)[:, None, :, :]
    M1 = _edge_masks(k1, shape4, L, drop_req, eye)
    M2 = _edge_masks(k2, shape4, L, drop_req, eye)
    M3 = _edge_masks(k3, shape4, L, drop_req, eye)
    R1 = _edge_masks(k1r, shape4, L, drop_rep, eye)
    R2 = _edge_masks(k2r, shape4, L, drop_rep, eye)

    C = min(8 * LANES, max(LANES, ((N + LANES - 1) // LANES) * LANES))
    Np = ((N + C - 1) // C) * C

    st = [
        _to_lanes(state.np_, P, N, Np, 0),
        _to_lanes(state.na, P, N, Np, 0),
        _to_lanes(state.va, P, N, Np, NO_VAL),
        _to_lanes(state.decided, P, N, Np, NO_VAL),
        _to_lanes(state.active, P, N, Np, 0),
        _to_lanes(state.propv, P, N, Np, NO_VAL),
        _to_lanes(state.maxseen, P, N, Np, 0),
    ]
    masks = [_mask_to_lanes(m, P, N, Np) for m in (M1, M2, M3, R1, R2)]

    cell = pl.BlockSpec((P, C), lambda i: (0, i))
    edge_spec = pl.BlockSpec((P, P, C), lambda i: (0, 0, i))
    out_shape = jax.ShapeDtypeStruct((P, Np), I32)
    outs = pl.pallas_call(
        functools.partial(_round_kernel, P),
        grid=(Np // C,),
        in_specs=[cell] * 7 + [edge_spec] * 5,
        out_specs=[cell] * 6,
        out_shape=[out_shape] * 6,
        interpret=interpret,
    )(*st, *masks)
    np_post2, na_new, va_new, decided_l, maxseen_l, msgs_l = outs

    msgs = msgs_l[:, :N].sum().astype(I32)
    np_post2 = _from_lanes(np_post2, G, I, P, N)
    na_new = _from_lanes(na_new, G, I, P, N)
    va_new = _from_lanes(va_new, G, I, P, N)
    decided_new = _from_lanes(decided_l, G, I, P, N)
    maxseen = _from_lanes(maxseen_l, G, I, P, N)
    active_new = state.active & (decided_new < 0)

    # Done piggyback (paxos/rpc.go:74-80): rides prepare traffic + the
    # once-per-step heartbeat (bit-identical to the XLA path at drop=0, where
    # the heartbeat covers every live edge).
    anymsg1 = (M1 & state.active[..., :, None]).any(axis=1)  # (G, src, dst)
    hb = _edge_masks(khb, (G, P, P), (link | eye), drop_req, eye)
    gotmsg = jnp.swapaxes(anymsg1 | hb, -1, -2)
    done_view = jnp.maximum(state.done_view, jnp.where(gotmsg, done[:, None, :], -1))
    done_view = jnp.maximum(done_view, jnp.where(eye[None], done[:, None, :], -1))

    new_state = PaxosState(
        np_=np_post2, na=na_new, va=va_new, decided=decided_new,
        active=active_new, propv=state.propv, maxseen=maxseen,
        done_view=done_view,
    )
    touched = (np_post2 > 0) | (na_new > 0) | (decided_new >= 0) | active_new
    io = StepIO(decided=decided_new, done_view=done_view, touched=touched,
                msgs=msgs)
    return new_state, io


def resolve_impl(impl: str | None = None) -> str:
    """Resolve the step implementation name: 'xla' or 'pallas'.

    Default (no arg, no $TPU6824_KERNEL): 'pallas' on TPU — measured faster
    than the XLA path on the real chip (see bench.py) — and 'xla' elsewhere,
    since off-TPU the Pallas path runs in interpret mode (kept for the CPU
    equivalence suite, far too slow for service use).
    """
    import os

    on_tpu = jax.default_backend() in ("tpu", "axon")
    impl = impl or os.environ.get(
        "TPU6824_KERNEL", "pallas" if on_tpu else "xla"
    )
    if impl not in ("xla", "pallas"):
        raise ValueError(f"unknown kernel impl {impl!r}")
    return impl


def get_step(impl: str | None = None):
    """Step implementation for `resolve_impl(impl)` (see its docstring)."""
    from tpu6824.core.kernel import paxos_step

    if resolve_impl(impl) == "xla":
        return paxos_step
    on_tpu = jax.default_backend() in ("tpu", "axon")
    return functools.partial(paxos_step_pallas, interpret=not on_tpu)
