"""Pallas TPU kernel for the hot op: one fused Prepare→Accept→Decide round.

The XLA path (`tpu6824/core/kernel.py:paxos_step`) expresses the round as ~40
jnp ops over `(G, I, P, P)` intermediates and relies on XLA fusion.  This
module fuses the whole round into ONE Pallas kernel:

  - cells are laid out `(P, N)` with `N = G·I` on the lane axis, so every
    per-edge exchange is an elementwise VPU op over a `(1, C)` vector of
    cells; the tiny peer axis (P = 3..7) is statically unrolled;
  - each grid step loads a `C`-cell block of the 7 state arrays (plus, in
    lossy mode, ONE packed delivery-mask array) into VMEM, runs all three
    phases without touching HBM, and writes the 6 outputs — a single HBM
    round-trip per step versus the XLA path's chain of fused-but-separate
    kernels;
  - the 5 per-edge delivery masks (the reference harness's lossy network,
    `paxos/paxos.go:528-544`, as per-edge Bernoulli keeps) are packed as
    BITPLANES of a single int32 array — one mask operand instead of five,
    an ~5× cut in per-step mask HBM traffic.  They are generated with
    EXACTLY the same `jax.random` splits as the XLA path, so the consensus
    state (np/na/va/decided/maxseen) is bit-identical under the same key at
    any drop rate; `done_view` is bit-identical only at drop=0 — under loss
    its Done-piggyback rides only prepare+heartbeat traffic (see below) and
    is equivalent distributionally, not bit-for-bit
    (`test_lossy_done_view_liveness_distribution`);
  - when the caller knows the network is reliable and fully connected
    (`masked=False` — the best-case and contended bench configs), no mask
    is materialized at all: the kernel's edge predicate folds to constant
    True and per-step HBM traffic is just the 13 state arrays;
  - state can stay RESIDENT in the `(P, N)` lane layout across steps
    (`LaneState` + `paxos_step_lanes` + `apply_starts_lane`), eliminating
    the two full-state transposes per step the conversion wrappers pay;
  - the steady-state CYCLE (`paxos_cycle_lanes`) additionally fuses the
    recycle+arm pass into the same kernel (one HBM round trip for what
    was three), can draw lossy delivery bits from the in-kernel counter
    PRNG (mode="prng": zero mask HBM traffic, distributionally — not
    bit — equivalent to the XLA oracle), and can drop the RPC-budget
    counter output (`count_msgs=False`) for pure-throughput loops.

Semantics are those of `paxos_step` (see kernel.py's docstring for the
mapping to `paxos/paxos.go`); the only realization difference is that the
Done-piggyback (`paxos/rpc.go:74-80`) rides the heartbeat + prepare traffic
rather than all three phases' traffic — same information flow, fewer mask
materializations.

Select with `TPU6824_KERNEL=pallas` (see `tpu6824/config.py`); falls back to
interpret mode off-TPU so the CPU test suite can verify equivalence.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tpu6824.core.kernel import (
    NO_VAL, NPROTO, PROTO_ENABLED, PROTO_PACK_BITS, PROTO_PACK_SHIFT,
    PaxosState, StepIO, _edge_masks,
)

I32 = jnp.int32
LANES = 128  # TPU lane width; cell blocks are multiples of this

# Bitplane assignment inside the packed mask word.
_BIT_M1, _BIT_M2, _BIT_M3, _BIT_R1, _BIT_R2 = range(5)


def _round_kernel(P: int, mode: str, cycle: bool,
                  count_msgs: bool, proto: bool, *refs):
    """One consensus round for a (P, C) block of cells.

    `mode` selects the delivery-mask source:
      - "reliable": no masks at all — the edge predicate folds to constant
        True (zero mask HBM traffic);
      - "packed":   one (P, P, C) int32 bitplane input, bits 0..4 =
        M1, M2, M3, R1, R2 — generated XLA-side with the exact splits of
        the XLA oracle (bit-identical consensus state under the same key);
      - "prng":     NO mask input: delivery bits are drawn IN-KERNEL from
        the TPU's counter PRNG, seeded per (step, block) from a 3-int32
        SMEM config [seed, thresh_req, thresh_rep] (thresh = drop
        probability in 1/2^24 units).  Mask HBM traffic: zero.  Only
        distributionally equivalent to the oracle (different stream).

    `cycle=True` additionally fuses the bench/steady-state recycle+arm
    (`apply_starts_lane`) into the same VMEM round trip: cells whose `dec`
    is set are reset, then `sa/sv` arm proposers, then the round runs —
    one pass over HBM for what was previously three (recycle read, arm
    read/write, round read/write).  Outputs grow to include act/propv and
    a per-cell recycled indicator.

    `proto=True` (kernelscope) additionally writes a (P, C) packed
    per-cell EVENT WORD — the seven PROTO_FIELDS counts at their
    PROTO_PACK_SHIFT bit offsets, computed from the very same delivery/
    grant/win/decide booleans the round already holds in registers — so
    the caller can reduce per-group protocol totals XLA-side without a
    second pass over the state.  Events are bit-identical to the XLA
    round's reductions under the same masks (the two-engine parity
    contract).

    refs order: [cfg?] np, na, va, dec, act, propv, ms, [sa, sv], [mask],
    then outputs: np, na, va, dec, ms, [act, propv, rec], [msgs], [proto]
    (`count_msgs=False` drops the msgs output entirely — the RPC-budget
    counter is one full (P, C) write per block that steady-state
    throughput loops never read).
    State refs are (P, C) int32.  Every operand below is a (1, C) lane
    vector; loops over the peer axis are unrolled at trace time.
    """
    refs = list(refs)
    cfg_ref = refs.pop(0) if mode == "prng" else None
    (np_ref, na_ref, va_ref, dec_ref, act_ref, propv_ref, ms_ref) = refs[:7]
    refs = refs[7:]
    if cycle:
        sa_ref, sv_ref = refs[:2]
        refs = refs[2:]
    mask_ref = refs.pop(0) if mode == "packed" else None
    if cycle:
        (np_out, na_out, va_out, dec_out, ms_out,
         act_out, propv_out, rec_out) = refs[:8]
        refs = refs[8:]
    else:
        (np_out, na_out, va_out, dec_out, ms_out) = refs[:5]
        refs = refs[5:]
    msgs_out = refs.pop(0) if count_msgs else None
    proto_out = refs.pop(0) if proto else None

    C = np_ref.shape[1]

    def row(ref, p):
        return ref[p:p + 1, :]

    tru = jnp.ones((1, C), dtype=bool)
    if mode == "packed":
        def edge(bit, p, q):
            return ((mask_ref[p, q:q + 1, :] >> bit) & 1) != 0
    elif mode == "prng":
        # Seed once per (step, block): same step+block => same stream.
        pltpu.prng_seed(cfg_ref[0], pl.program_id(0))
        thresh = [cfg_ref[1], cfg_ref[1], cfg_ref[1],  # M1..M3: req drop
                  cfg_ref[2], cfg_ref[2]]              # R1, R2: reply drop
        # Draw every directed edge's keep bit up front, in a fixed trace
        # order (edge() below must be a pure read — several phases consult
        # the same plane twice).  Self-edges always deliver.
        planes = []
        for b in range(5):
            t = thresh[b]
            plane = []
            for p in range(P):
                prow = []
                for q in range(P):
                    if p == q:
                        prow.append(tru)
                    else:
                        bits = pltpu.prng_random_bits((1, C))
                        r = jax.lax.shift_right_logical(
                            bits.astype(I32), 8) & jnp.int32(0xFFFFFF)
                        prow.append(r >= t)
                plane.append(prow)
            planes.append(plane)

        def edge(bit, p, q):
            return planes[bit][p][q]
    else:  # reliable, fully-connected fast path: the edge predicate is
        # the constant True vector, which Mosaic folds out of every AND.
        def edge(bit, p, q):
            return tru

    np_pre = [row(np_ref, p) for p in range(P)]
    na_pre = [row(na_ref, p) for p in range(P)]
    va_pre = [row(va_ref, p) for p in range(P)]
    dec_pre = [row(dec_ref, p) for p in range(P)]
    active = [row(act_ref, p) != 0 for p in range(P)]
    propv = [row(propv_ref, p) for p in range(P)]
    maxseen = [row(ms_ref, p) for p in range(P)]

    if cycle:
        # ---- Fused recycle + arm (apply_starts_lane semantics) ----------
        rec = dec_pre[0] >= 0
        for p in range(1, P):
            rec = rec | (dec_pre[p] >= 0)
        zero_ = jnp.zeros((1, C), I32)
        noval = zero_ + NO_VAL
        np_pre = [jnp.where(rec, zero_, x) for x in np_pre]
        na_pre = [jnp.where(rec, zero_, x) for x in na_pre]
        va_pre = [jnp.where(rec, noval, x) for x in va_pre]
        dec_pre = [jnp.where(rec, noval, x) for x in dec_pre]
        active = [a & ~rec for a in active]
        propv = [jnp.where(rec, noval, x) for x in propv]
        maxseen = [jnp.where(rec, zero_, x) for x in maxseen]
        for p in range(P):
            arm = (row(sa_ref, p) != 0) & (dec_pre[p] < 0)
            active[p] = active[p] | arm
            propv[p] = jnp.where(arm & (propv[p] < 0), row(sv_ref, p),
                                 propv[p])

    # n = k·P + p + 1: globally unique, > maxseen (kernel.py:137).
    n_prop = [(maxseen[p] // P + 1) * P + (p + 1) for p in range(P)]

    zero = jnp.zeros_like(np_pre[0])

    # ---- Phase 1: PREPARE --------------------------------------------------
    # Delivery: D1[p→q]; promise iff n_prop[p] > np_pre[q] (paxos.go:244-257).
    D1 = [[edge(_BIT_M1, p, q) & active[p] for q in range(P)]
          for p in range(P)]
    np_post1 = []
    for q in range(P):
        hi = np_pre[q]
        for p in range(P):
            hi = jnp.maximum(hi, jnp.where(D1[p][q], n_prop[p], 0))
        np_post1.append(hi)

    maj1, v1 = [], []
    for p in range(P):
        cnt = zero
        best_na = zero - 1
        va_best = propv[p]
        for q in range(P):
            grant = D1[p][q] & (n_prop[p] > np_pre[q])
            got = grant & edge(_BIT_R1, p, q)
            cnt = cnt + got.astype(I32)
            cand = jnp.where(got, na_pre[q], -1)
            upd = cand > best_na
            best_na = jnp.where(upd, cand, best_na)
            va_best = jnp.where(upd, va_pre[q], va_best)
        maj1.append(cnt * 2 > P)
        # Adopt highest accepted value among promisers (paxos.go:166-189).
        v1.append(jnp.where(best_na > 0, va_best, propv[p]))

    ms_new = []
    for p in range(P):
        hi = maxseen[p]
        for q in range(P):
            rep = D1[p][q] & edge(_BIT_R1, p, q)
            hi = jnp.maximum(hi, jnp.where(rep, np_post1[q], 0))
        ms_new.append(hi)

    # ---- Phase 2: ACCEPT ---------------------------------------------------
    # Accept iff n >= promised; one winner per acceptor per step — the
    # highest delivered n (per-step serialization rule, kernel.py:168-173).
    send2 = [active[p] & maj1[p] for p in range(P)]
    D2 = [[edge(_BIT_M2, p, q) & send2[p] for q in range(P)]
          for p in range(P)]
    ok2 = [[D2[p][q] & (n_prop[p] >= np_post1[q]) for q in range(P)]
           for p in range(P)]
    win_n = []
    for q in range(P):
        hi = zero
        for p in range(P):
            hi = jnp.maximum(hi, jnp.where(ok2[p][q], n_prop[p], 0))
        win_n.append(hi)
    win = [[ok2[p][q] & (n_prop[p] == win_n[q]) for q in range(P)]
           for p in range(P)]

    np_post2, na_new, va_new = [], [], []
    for q in range(P):
        any_acc = win_n[q] > 0
        np_post2.append(jnp.maximum(np_post1[q], win_n[q]))
        na_new.append(jnp.where(any_acc, win_n[q], na_pre[q]))
        va_win = zero
        for p in range(P):
            va_win = va_win + jnp.where(win[p][q], v1[p], 0)
        va_new.append(jnp.where(any_acc, va_win, va_pre[q]))

    maj2 = []
    for p in range(P):
        cnt = zero
        for q in range(P):
            cnt = cnt + (win[p][q] & edge(_BIT_R2, p, q)).astype(I32)
        maj2.append(cnt * 2 > P)
        hi = ms_new[p]
        for q in range(P):
            rep = D2[p][q] & edge(_BIT_R2, p, q)
            hi = jnp.maximum(hi, jnp.where(rep, np_post2[q], 0))
        ms_new[p] = hi

    # ---- Phase 3: DECIDE + gossip (kernel.py:185-195) ----------------------
    all_dec = dec_pre[0] >= 0
    for p in range(1, P):
        all_dec = all_dec & (dec_pre[p] >= 0)
    decider = [send2[p] & maj2[p] for p in range(P)]
    dv = [jnp.where(decider[p], v1[p], dec_pre[p]) for p in range(P)]
    send3 = [decider[p] | ((dec_pre[p] >= 0) & ~all_dec) for p in range(P)]
    D3 = [[edge(_BIT_M3, p, q) & send3[p] for q in range(P)]
          for p in range(P)]
    dec_new = []
    for q in range(P):
        inc = zero + NO_VAL
        for p in range(P):
            inc = jnp.maximum(inc, jnp.where(D3[p][q], dv[p], NO_VAL))
        dec_new.append(jnp.where(dec_pre[q] >= 0, dec_pre[q], inc))

    # Remote-message count per sender (self edges excluded) — RPC budget
    # analog (paxos/test_test.go:503-573).
    if count_msgs:
        msgs = []
        for p in range(P):
            cnt = zero
            for q in range(P):
                if q == p:
                    continue
                cnt = (cnt + D1[p][q].astype(I32) + D2[p][q].astype(I32)
                       + D3[p][q].astype(I32))
            msgs.append(cnt)

    # ---- kernelscope packed event word (PROTO_FIELDS order) ----------------
    # One int32 per cell carrying every protocol event of this step, from
    # booleans already in registers — the device-resident telemetry's whole
    # per-step cost is this pack + one (P, C) write per block.
    if proto:
        (s_att, s_prej, s_arej, s_qf,
         s_rst, s_dec, s_fast) = PROTO_PACK_SHIFT
        words = []
        for p in range(P):
            prej = zero
            arej = zero
            for q in range(P):
                prej = prej + (D1[p][q]
                               & ~(n_prop[p] > np_pre[q])).astype(I32)
                arej = arej + (D2[p][q] & ~win[p][q]).astype(I32)
            words.append(
                active[p].astype(I32) << s_att
                | prej << s_prej
                | arej << s_arej
                | ((active[p] & ~maj1[p]).astype(I32)
                   + (send2[p] & ~maj2[p]).astype(I32)) << s_qf
                | (active[p] & (dec_new[p] < 0)).astype(I32) << s_rst
                | decider[p].astype(I32) << s_dec
                | (decider[p]
                   & (n_prop[p] <= 2 * P)).astype(I32) << s_fast)
        proto_out[...] = jnp.concatenate(words, axis=0)

    np_out[...] = jnp.concatenate(np_post2, axis=0)
    na_out[...] = jnp.concatenate(na_new, axis=0)
    va_out[...] = jnp.concatenate(va_new, axis=0)
    dec_out[...] = jnp.concatenate(dec_new, axis=0)
    ms_out[...] = jnp.concatenate(ms_new, axis=0)
    if count_msgs:
        msgs_out[...] = jnp.concatenate(msgs, axis=0)
    if cycle:
        act_out[...] = jnp.concatenate(
            [(active[p] & (dec_new[p] < 0)).astype(I32) for p in range(P)],
            axis=0)
        propv_out[...] = jnp.concatenate(propv, axis=0)
        rec_out[...] = rec.astype(I32)


# --------------------------------------------------------------------------
# lane layout


class LaneState(NamedTuple):
    """Consensus state resident in the kernel's (P, Np) lane layout —
    cells (g·I + i) on lanes, peers on sublanes, padded to the block size.
    Conversions to/from PaxosState cost two full-state transposes; keep
    state in this form across steps (bench loops, lax.scan) and convert
    only at the boundary."""

    np_: jnp.ndarray     # (P, Np) i32
    na: jnp.ndarray      # (P, Np) i32
    va: jnp.ndarray      # (P, Np) i32
    dec: jnp.ndarray     # (P, Np) i32
    act: jnp.ndarray     # (P, Np) i32 (0/1)
    propv: jnp.ndarray   # (P, Np) i32
    ms: jnp.ndarray      # (P, Np) i32


def _block(N: int) -> tuple[int, int]:
    """(block size C, padded cell count Np) for an N-cell universe.

    TPU6824_BLOCK_CELLS overrides the per-grid-step cell count (rounded to
    lane multiples) — the tuning knob for block-size sweeps on hardware:
    bigger blocks amortize grid overhead and lengthen DMA bursts at the
    cost of VMEM residency (~4 bytes x ~17 lane rows per cell).

    Read at TRACE time: jit caches key on shapes, so changing the knob
    inside one process is ignored whenever the padded Np is unchanged —
    sweep across fresh processes (as bench.py runs do), not in-process."""
    import os

    raw = os.environ.get("TPU6824_BLOCK_CELLS") or str(8 * LANES)
    try:
        cap = int(raw)
    except ValueError as e:
        raise ValueError(
            f"TPU6824_BLOCK_CELLS={raw!r} is not an integer") from e
    cap = max(LANES, (cap // LANES) * LANES)
    C = min(cap, max(LANES, ((N + LANES - 1) // LANES) * LANES))
    return C, ((N + C - 1) // C) * C


def _to_lanes(a, P, N, Np, fill):
    """(G, I, P) → (P, Np) int32, cells on lanes, padded with `fill`."""
    a = jnp.moveaxis(a, 2, 0).reshape(P, N).astype(I32)
    if Np != N:
        a = jnp.pad(a, ((0, 0), (0, Np - N)), constant_values=fill)
    return a


def _mask_to_lanes(m, P, N, Np):
    """(G, I, P, P) int32 → (P, P, Np) [src, dst, cell]."""
    m = jnp.moveaxis(m.reshape(N, P, P), 0, 2).astype(I32)
    if Np != N:
        m = jnp.pad(m, ((0, 0), (0, 0), (0, Np - N)), constant_values=0)
    return m


def _from_lanes(a, G, I, P, N):
    return jnp.moveaxis(a[:, :N].reshape(P, G, I), 0, 2)


def to_lane_state(state: PaxosState) -> LaneState:
    """Transpose a PaxosState into lane residency (done_view stays with the
    caller — it is (G, P, P) host/XLA-side state, not a kernel operand)."""
    G, I, P = state.np_.shape
    N = G * I
    _, Np = _block(N)
    return LaneState(
        np_=_to_lanes(state.np_, P, N, Np, 0),
        na=_to_lanes(state.na, P, N, Np, 0),
        va=_to_lanes(state.va, P, N, Np, NO_VAL),
        dec=_to_lanes(state.decided, P, N, Np, NO_VAL),
        act=_to_lanes(state.active, P, N, Np, 0),
        propv=_to_lanes(state.propv, P, N, Np, NO_VAL),
        ms=_to_lanes(state.maxseen, P, N, Np, 0),
    )


def from_lane_state(l: LaneState, done_view: jnp.ndarray,
                    G: int, I: int) -> PaxosState:
    P = l.np_.shape[0]
    N = G * I
    return PaxosState(
        np_=_from_lanes(l.np_, G, I, P, N),
        na=_from_lanes(l.na, G, I, P, N),
        va=_from_lanes(l.va, G, I, P, N),
        decided=_from_lanes(l.dec, G, I, P, N),
        active=_from_lanes(l.act, G, I, P, N) != 0,
        propv=_from_lanes(l.propv, G, I, P, N),
        maxseen=_from_lanes(l.ms, G, I, P, N),
        done_view=done_view,
    )


@jax.jit
def apply_starts_lane(l: LaneState, reset: jnp.ndarray,
                      start_active: jnp.ndarray,
                      start_val: jnp.ndarray) -> LaneState:
    """`apply_starts` (kernel.py) in lane residency.

    reset: (Np,) bool — recycle these cells (window GC);
    start_active: (P, Np) 0/1; start_val: (P, Np) i32.
    """
    r = reset[None, :]
    np_ = jnp.where(r, 0, l.np_)
    na = jnp.where(r, 0, l.na)
    va = jnp.where(r, NO_VAL, l.va)
    dec = jnp.where(r, NO_VAL, l.dec)
    act = jnp.where(r, 0, l.act)
    propv = jnp.where(r, NO_VAL, l.propv)
    ms = jnp.where(r, 0, l.ms)
    sa = start_active != 0
    act = ((act != 0) | (sa & (dec < 0))).astype(I32)
    propv = jnp.where(sa & (propv < 0), start_val, propv)
    return LaneState(np_=np_, na=na, va=va, dec=dec, act=act,
                     propv=propv, ms=ms)


def _lane_round(l: LaneState, packed_mask, interpret,
                *, mode=None, cycle=False, sa=None, sv=None, cfg=None,
                count_msgs=True, proto=False):
    """Invoke the fused round on lane-resident state.

    Back-compat form: `packed_mask` is the (P, P, Np) int32 bitplane array
    (mode="packed") or None (mode="reliable").  `mode` overrides when
    given.  With `cycle=True`, sa/sv (P, Np) i32 arm inputs are fused in
    and the return gains the per-cell recycled vector (see _round_kernel).
    With `proto=True` the return additionally gains the (P, Np) packed
    per-cell event-word array (kernelscope; unpack per group with
    `_unpack_proto`).  mode="prng" requires `cfg` = int32[3]
    [seed, thresh_req, thresh_rep] and, off-TPU, the TPU interpreter
    (plain interpret mode has no PRNG rules; InterpretParams emulates
    them — degenerately, all-zero bits)."""
    P, Np = l.np_.shape
    C, _ = _block(Np)  # Np is already block-aligned
    if proto and P > 15:
        raise ValueError(
            f"kernelscope event-word packing holds reject counts in 4 "
            f"bits (P <= 15); got P={P}")
    if mode is None:
        mode = "packed" if packed_mask is not None else "reliable"
    if mode == "prng" and interpret is True:
        ip = getattr(pltpu, "InterpretParams", None)
        if ip is None:  # jax < 0.5: no TPU-interpreter PRNG emulation
            raise NotImplementedError(
                "mode='prng' off-TPU needs pallas TPU InterpretParams "
                "(newer jax); use mode='packed' on CPU")
        interpret = ip()

    cell = pl.BlockSpec((P, C), lambda i: (0, i))
    edge_spec = pl.BlockSpec((P, P, C), lambda i: (0, 0, i))
    out_shape = jax.ShapeDtypeStruct((P, Np), I32)
    ops = []
    in_specs = []
    if mode == "prng":
        ops.append(cfg)
        in_specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))
    ops += [l.np_, l.na, l.va, l.dec, l.act, l.propv, l.ms]
    in_specs += [cell] * 7
    if cycle:
        ops += [sa, sv]
        in_specs += [cell, cell]
    if mode == "packed":
        ops.append(packed_mask)
        in_specs.append(edge_spec)
    rec_spec = pl.BlockSpec((1, C), lambda i: (0, i))
    if cycle:
        # np, na, va, dec, ms, act, propv, rec, [msgs]
        out_specs = [cell] * 7 + [rec_spec]
        out_shape_l = [out_shape] * 7 + [jax.ShapeDtypeStruct((1, Np), I32)]
    else:
        out_specs = [cell] * 5
        out_shape_l = [out_shape] * 5
    if count_msgs:
        out_specs.append(cell)
        out_shape_l.append(out_shape)
    if proto:
        out_specs.append(cell)
        out_shape_l.append(out_shape)
    outs = list(pl.pallas_call(
        functools.partial(_round_kernel, P, mode, cycle, count_msgs, proto),
        grid=(Np // C,),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape_l,
        interpret=interpret,
    )(*ops))
    if cycle:
        (np_post2, na_new, va_new, dec_new, ms_new,
         act_new, propv_new, rec) = outs[:8]
        outs = outs[8:]
    else:
        (np_post2, na_new, va_new, dec_new, ms_new) = outs[:5]
        outs = outs[5:]
    msgs_l = outs.pop(0) if count_msgs else None
    proto_pk = outs.pop(0) if proto else None
    if cycle:
        l2 = LaneState(np_=np_post2, na=na_new, va=va_new, dec=dec_new,
                       act=act_new, propv=propv_new, ms=ms_new)
        return (l2, msgs_l, rec, proto_pk) if proto else (l2, msgs_l, rec)
    act_new = ((l.act != 0) & (dec_new < 0)).astype(I32)
    l2 = LaneState(np_=np_post2, na=na_new, va=va_new, dec=dec_new,
                   act=act_new, propv=l.propv, ms=ms_new)
    return (l2, msgs_l, proto_pk) if proto else (l2, msgs_l)


def _unpack_proto(packed, G: int, I: int, P: int):
    """(P, Np) packed per-cell event words → (G, NPROTO) per-group totals
    in PROTO_FIELDS order.  Pure XLA reductions inside the caller's jit —
    the per-group fold costs no extra dispatch and no readback.  Pad
    cells are inactive and emit all-zero words, so no masking is needed
    beyond the slice to the live N cells."""
    N = G * I
    w = packed[:, :N]
    cols = []
    for bits, shift in zip(PROTO_PACK_BITS, PROTO_PACK_SHIFT):
        f = (w >> shift) & ((1 << bits) - 1)
        cols.append(f.reshape(P, G, I).sum(axis=(0, 2)))
    return jnp.stack(cols, axis=-1).astype(I32)


def _pack_masks(key, G, I, P, link, drop_req, drop_rep, Np):
    """Generate the five delivery masks with the XLA path's exact splits
    (kernel.py:123) and pack them into one (P, P, Np) int32 bitplane array.
    Returns (packed, M1, heartbeat_key) — the caller reduces M1 against the
    active cells to derive the Done-piggyback's anymsg1."""
    N = G * I
    eye = jnp.eye(P, dtype=bool)
    shape4 = (G, I, P, P)
    k1, k2, k3, k1r, k2r, _k3r, khb = jax.random.split(key, 7)
    L = (link | eye)[:, None, :, :]
    M1 = _edge_masks(k1, shape4, L, drop_req, eye)
    M2 = _edge_masks(k2, shape4, L, drop_req, eye)
    M3 = _edge_masks(k3, shape4, L, drop_req, eye)
    R1 = _edge_masks(k1r, shape4, L, drop_rep, eye)
    R2 = _edge_masks(k2r, shape4, L, drop_rep, eye)
    packed4 = (M1.astype(I32) << _BIT_M1 | M2.astype(I32) << _BIT_M2
               | M3.astype(I32) << _BIT_M3 | R1.astype(I32) << _BIT_R1
               | R2.astype(I32) << _BIT_R2)
    packed = _mask_to_lanes(packed4, P, N, Np)
    return packed, M1, khb


@functools.partial(jax.jit,
                   static_argnames=("G", "I", "masked", "interpret",
                                    "with_proto"))
def paxos_step_lanes(
    l: LaneState,
    done_view: jnp.ndarray,  # (G, P, P) i32
    link: jnp.ndarray,       # (G, P, P) bool
    done: jnp.ndarray,       # (G, P) i32
    key: jnp.ndarray,
    drop_req: jnp.ndarray,   # (G, P, P) f32
    drop_rep: jnp.ndarray,   # (G, P, P) f32
    *,
    G: int,
    I: int,
    masked: bool = True,
    interpret: bool = False,
    with_proto: bool = False,
):
    """One fused round on lane-resident state.

    masked=True: full fault semantics, bit-identical to the XLA path under
    the same key.  masked=False: reliable fully-connected fast path (link
    and drops are ignored — caller asserts the network is perfect), zero
    mask HBM traffic.

    Returns (LaneState, done_view, msgs) — decided values live in the
    returned state's `.dec`.  With `with_proto=True` (kernelscope) the
    return gains a fourth element: the (G, NPROTO) per-group protocol
    event totals, packed in-kernel and unpacked here inside the same jit
    (no extra dispatch, no readback).
    """
    P = l.np_.shape[0]
    N = G * I
    eye = jnp.eye(P, dtype=bool)

    if masked:
        packed, M1, khb = _pack_masks(
            key, G, I, P, link, drop_req, drop_rep, l.np_.shape[1])
        out = _lane_round(l, packed, interpret, proto=with_proto)
        # Done piggyback (paxos/rpc.go:74-80): rides prepare traffic + the
        # once-per-step heartbeat (bit-identical to the XLA path at drop=0,
        # where the heartbeat covers every live edge).
        done_view = _done_gossip_packed(
            l.act, M1, khb, link, drop_req, done_view, done, G, I, P, N,
            eye)
    else:
        out = _lane_round(l, None, interpret, proto=with_proto)
        # Reliable full mesh: every peer hears every peer each step.
        done_view = jnp.maximum(done_view, done[:, None, :])
    if with_proto:
        l2, msgs_l, proto_pk = out
    else:
        l2, msgs_l = out
    done_view = jnp.maximum(
        done_view, jnp.where(eye[None], done[:, None, :], -1))
    msgs = msgs_l[:, :N].sum().astype(I32)
    if with_proto:
        return l2, done_view, msgs, _unpack_proto(proto_pk, G, I, P)
    return l2, done_view, msgs


def _done_gossip_packed(act_lanes, M1, khb, link, drop_req, done_view, done,
                        G, I, P, N, eye):
    """Done piggyback (paxos/rpc.go:74-80) for packed-mask rounds: rides
    the prepare traffic of the given (post-arm) active set plus the
    once-per-step heartbeat over live links.  Shared by the step and the
    fused cycle so the two paths cannot drift."""
    act_gip = _from_lanes(act_lanes, G, I, P, N) != 0
    anymsg1 = (M1 & act_gip[..., :, None]).any(axis=1)  # (G, src, dst)
    hb = _edge_masks(khb, (G, P, P), (link | eye), drop_req, eye)
    gotmsg = jnp.swapaxes(anymsg1 | hb, -1, -2)
    return jnp.maximum(done_view, jnp.where(gotmsg, done[:, None, :], -1))


def paxos_cycle_lanes(l, done_view, done, key, sa, sv, link=None,
                      drop_req=None, drop_rep=None, *, G, I,
                      mode="reliable", req_rate=0.0, rep_rate=0.0,
                      interpret=False, count_msgs=True):
    """Guarded entry for the fused cycle (`_paxos_cycle_lanes` holds the
    real docstring).  mode='prng' under interpret uses InterpretParams,
    whose PRNG emulation yields all-zero bits: any nonzero drop threshold
    then fails every non-self `r >= thresh` check and consensus silently
    livelocks — fail loudly instead and point at mode='packed', which is
    the off-TPU lossy path (ADVICE r4)."""
    if mode == "prng" and interpret:
        try:
            lossy = float(req_rate) > 0.0 or float(rep_rate) > 0.0
        except (TypeError, jax.errors.TracerArrayConversionError,
                jax.errors.ConcretizationTypeError):
            # Traced rates (e.g. bench's jitted run_j): cannot prove zero
            # at trace time — fail loudly rather than risk the silent
            # corner; bench's prng→packed demotion handler catches this.
            lossy = True
        if lossy:
            raise ValueError(
                "paxos_cycle_lanes(mode='prng') under interpret draws "
                "all-zero PRNG bits (pltpu.InterpretParams emulation): a "
                "nonzero (or traced, unprovably-zero) drop rate would "
                "deliver no messages and livelock silently.  Use "
                "mode='packed' off-TPU for lossy networks.")
    return _paxos_cycle_lanes(l, done_view, done, key, sa, sv, link,
                              drop_req, drop_rep, G=G, I=I, mode=mode,
                              req_rate=req_rate, rep_rate=rep_rate,
                              interpret=interpret, count_msgs=count_msgs)


@functools.partial(jax.jit, static_argnames=("G", "I", "mode", "interpret",
                                             "count_msgs"))
def _paxos_cycle_lanes(
    l: LaneState,
    done_view: jnp.ndarray,  # (G, P, P) i32
    done: jnp.ndarray,       # (G, P) i32
    key: jnp.ndarray,        # per-step PRNG key
    sa: jnp.ndarray,         # (P, Np) i32 — arm pattern for recycled cells
    sv: jnp.ndarray,         # (P, Np) i32 — arm values
    link=None,               # (G, P, P) bool — packed mode only
    drop_req=None,           # (G, P, P) f32 — packed mode only
    drop_rep=None,           # (G, P, P) f32 — packed mode only
    *,
    G: int,
    I: int,
    mode: str = "reliable",
    req_rate=0.0,            # prng mode: uniform request-drop probability
    rep_rate=0.0,            # prng mode: uniform reply-drop probability
    interpret=False,
    count_msgs: bool = True,
):
    """One fused steady-state CYCLE: recycle decided cells → arm via sa/sv
    → full prepare/accept/decide round — a single HBM round trip for what
    `apply_starts_lane` + `paxos_step_lanes` do in three (VERDICT r3
    roofline item: the bench cycle's true traffic was ~2x the round's).

    mode="prng" additionally draws the lossy-network delivery bits from
    the in-kernel counter PRNG (seeded per step+block from `key`), so the
    unreliable path's HBM traffic is the state arrays and nothing else —
    no (G, I, P, P) Bernoulli materialization, no packed bitplanes
    (VERDICT r3 task 2; the reference behavior being modeled is the
    accept-loop coin flip, paxos/paxos.go:528-544).  The XLA path stays
    the bit-exact oracle; prng mode is distributionally equivalent.
    Assumes a fully-connected link (the bench's unreliable config);
    partitioned/heterogeneous networks use mode="packed".

    Returns (LaneState, done_view, recycled (1, Np) i32, msgs scalar —
    or -1 with `count_msgs=False`, which drops the RPC-budget counter's
    (P, Np) write + reduce from the kernel for pure-throughput loops).
    """
    P = l.np_.shape[0]
    N = G * I
    eye = jnp.eye(P, dtype=bool)
    full = jnp.ones((G, P, P), bool)

    if mode == "packed":
        packed, M1, khb = _pack_masks(
            key, G, I, P, link, drop_req, drop_rep, l.np_.shape[1])
        # The round's prepare senders are the POST-recycle/arm actives
        # (the fused kernel recycles and arms before phase 1); recompute
        # that view here for the Done piggyback so packed-mode cycle and
        # split apply_starts_lane+paxos_step_lanes agree on done_view.
        rec_pre = (l.dec >= 0).any(axis=0)[None, :]      # (1, Np)
        act_post = (((l.act != 0) & ~rec_pre)
                    | ((sa != 0) & (rec_pre | (l.dec < 0))))
        l2, msgs_l, rec = _lane_round(l, packed, interpret, cycle=True,
                                      sa=sa, sv=sv, count_msgs=count_msgs)
        done_view = _done_gossip_packed(
            act_post, M1, khb, link, drop_req, done_view, done,
            G, I, P, N, eye)
    elif mode == "prng":
        # 24-bit drop thresholds; the kernel keeps an edge iff its draw's
        # bits 8..31 >= thresh.
        scale = jnp.float32(1 << 24)
        tq = jnp.clip(jnp.round(jnp.float32(req_rate) * scale),
                      0, scale).astype(I32)
        tp = jnp.clip(jnp.round(jnp.float32(rep_rate) * scale),
                      0, scale).astype(I32)
        seed = jax.lax.bitcast_convert_type(
            jax.random.key_data(key).ravel()[-1], jnp.int32)
        cfg = jnp.stack([seed, tq, tp])
        l2, msgs_l, rec = _lane_round(l, None, interpret, mode="prng",
                                      cycle=True, sa=sa, sv=sv, cfg=cfg,
                                      count_msgs=count_msgs)
        # Done piggyback: once-per-step heartbeat over the lossy net (the
        # kernel's deliveries aren't observable host-side in this mode —
        # same information flow, one gossip opportunity per step).
        hbdrop = jnp.full((G, P, P), req_rate, jnp.float32)
        hb = _edge_masks(key, (G, P, P), full, hbdrop, eye)
        gotmsg = jnp.swapaxes(hb, -1, -2)
        done_view = jnp.maximum(
            done_view, jnp.where(gotmsg, done[:, None, :], -1))
    else:
        l2, msgs_l, rec = _lane_round(l, None, interpret, cycle=True,
                                      sa=sa, sv=sv, count_msgs=count_msgs)
        done_view = jnp.maximum(done_view, done[:, None, :])
    done_view = jnp.maximum(
        done_view, jnp.where(eye[None], done[:, None, :], -1))
    msgs = (msgs_l[:, :N].sum().astype(I32) if count_msgs
            else jnp.int32(-1))
    return l2, done_view, rec[:, :N], msgs


@functools.partial(jax.jit, static_argnames=("interpret",))
def paxos_step_pallas(
    state: PaxosState,
    link: jnp.ndarray,       # (G, P, P) bool
    done: jnp.ndarray,       # (G, P) i32
    key: jnp.ndarray,
    drop_req: jnp.ndarray,   # (G, P, P) f32
    drop_rep: jnp.ndarray,   # (G, P, P) f32
    interpret: bool = False,
) -> tuple[PaxosState, StepIO]:
    """Drop-in replacement for `paxos_step` (same (G, I, P) layout and
    StepIO contract) with the round fused in Pallas.  Pays the lane
    transposes both ways; loops that step repeatedly should hold a
    LaneState and call `paxos_step_lanes` instead."""
    G, I, P = state.np_.shape
    l = to_lane_state(state)
    if PROTO_ENABLED:
        l2, done_view, msgs, proto = paxos_step_lanes(
            l, state.done_view, link, done, key, drop_req, drop_rep,
            G=G, I=I, masked=True, interpret=interpret, with_proto=True)
    else:
        l2, done_view, msgs = paxos_step_lanes(
            l, state.done_view, link, done, key, drop_req, drop_rep,
            G=G, I=I, masked=True, interpret=interpret)
        proto = jnp.zeros((G, NPROTO), I32)
    new_state = from_lane_state(l2, done_view, G, I)
    new_state = new_state._replace(propv=state.propv)
    touched = ((new_state.np_ > 0) | (new_state.na > 0)
               | (new_state.decided >= 0) | new_state.active)
    io = StepIO(decided=new_state.decided, done_view=done_view,
                touched=touched, msgs=msgs, proto=proto)
    return new_state, io


def resolve_impl(impl: str | None = None) -> str:
    """Resolve the step implementation name: 'xla' or 'pallas'.

    Default (no arg, no $TPU6824_KERNEL): 'pallas' on TPU — measured faster
    than the XLA path on the real chip (see bench.py) — and 'xla' elsewhere,
    since off-TPU the Pallas path runs in interpret mode (kept for the CPU
    equivalence suite, far too slow for service use).
    """
    import os

    on_tpu = jax.default_backend() in ("tpu", "axon")
    impl = impl or os.environ.get(
        "TPU6824_KERNEL", "pallas" if on_tpu else "xla"
    )
    if impl not in ("xla", "pallas"):
        raise ValueError(f"unknown kernel impl {impl!r}")
    return impl


def get_step(impl: str | None = None):
    """Step implementation for `resolve_impl(impl)` (see its docstring)."""
    from tpu6824.core.kernel import paxos_step

    if resolve_impl(impl) == "xla":
        return paxos_step
    on_tpu = jax.default_backend() in ("tpu", "axon")
    return functools.partial(paxos_step_pallas, interpret=not on_tpu)
