"""tpu6824.analysis — tpusan: lock-discipline & determinism analyzer.

Four tools, one package:

  - `lint` — the project-specific per-file AST pass (`python -m
    tpu6824.analysis <paths>`): lock-region blocking calls, per-cell
    loops under the fabric lock, nondeterminism in schedule-replay
    paths, silent daemon deaths, columnar-feed contract, tracer leaks.
    Stdlib only — no JAX import, fast enough for tier-1.
  - `consan` — the whole-program concurrency pass (same CLI): thread
    entry points propagated through the call graph, a static
    interprocedural lock-order graph checked for cycles and against
    the canonical `tpu6824.utils.locks.MANIFEST`, lock-protection
    inconsistencies (attr written under a lock, touched lock-free from
    another thread class), and blocking calls reachable while a server
    mutex is held.
  - `lockwatch` — opt-in runtime lock-order/hold-time sanitizer
    (`TPU6824_SANITIZE=1` / the `sanitize` pytest fixture), now also
    enforcing the lock manifest's acquisition order live.
  - `jitguard` — steady-state recompile guard (lazy JAX import).

`ANALYZER_VERSION`/`CONSAN_VERSION` stamp reports and CHANGES-style
artifacts so rule additions stay auditable across PRs.
"""

from tpu6824.analysis.consan import (  # noqa: F401
    CONSAN_RULES,
    CONSAN_VERSION,
    Analysis,
    analyze_paths,
    merged_cycles,
)
from tpu6824.analysis.lint import (  # noqa: F401
    ANALYZER_VERSION,
    Finding,
    RULES,
    WHOLE_PROGRAM_RULES,
    lint_file,
    lint_paths,
    lint_source,
)

__all__ = [
    "ANALYZER_VERSION",
    "Analysis",
    "CONSAN_RULES",
    "CONSAN_VERSION",
    "Finding",
    "RULES",
    "WHOLE_PROGRAM_RULES",
    "analyze_paths",
    "lint_file",
    "lint_paths",
    "lint_source",
    "merged_cycles",
]
