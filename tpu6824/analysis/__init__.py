"""tpu6824.analysis — tpusan: lock-discipline & determinism analyzer.

Three tools, one package:

  - `lint` — the project-specific AST pass (`python -m tpu6824.analysis
    <paths>`): lock-region blocking calls, per-cell loops under the
    fabric lock, nondeterminism in schedule-replay paths, silent daemon
    deaths, columnar-feed contract, tracer leaks.  Stdlib only — no JAX
    import, fast enough for tier-1.
  - `lockwatch` — opt-in runtime lock-order/hold-time sanitizer
    (`TPU6824_SANITIZE=1` / the `sanitize` pytest fixture).
  - `jitguard` — steady-state recompile guard (lazy JAX import).

`ANALYZER_VERSION` stamps reports and CHANGES-style artifacts so rule
additions stay auditable across PRs.
"""

from tpu6824.analysis.lint import (  # noqa: F401
    ANALYZER_VERSION,
    Finding,
    RULES,
    lint_file,
    lint_paths,
    lint_source,
)

__all__ = [
    "ANALYZER_VERSION",
    "Finding",
    "RULES",
    "lint_file",
    "lint_paths",
    "lint_source",
]
