"""lockwatch — runtime lock-order and hold-time sanitizer.

The Go reference gets `-race` for free; this is the slice of it the
threaded host runtime actually needs: every watched lock acquisition
records (per thread) the set of locks already held, building a global
lock ACQUISITION GRAPH whose nodes are lock instances and whose edge
a→b means "some thread acquired b while holding a".  A cycle in that
graph is deadlock potential — two threads interleaving the cycle's
edges block forever — even if the test run happened not to interleave
them.  Watched locks can also carry a HOLD-TIME BUDGET: holding the
fabric lock longer than its budget is the PR 2 regression class (a
per-cell Python loop under `PaxosFabric._lock` halved clerk
throughput), reported here as a violation instead of a TUNING.md
post-mortem.

Opt-in, two layers:

  - `TPU6824_SANITIZE=1` (or the `sanitize` pytest fixture) calls
    `enable()`, which patches `threading.Lock` / `threading.RLock` so
    every lock created AFTERWARDS is watched (anonymous locks get a
    creation-site label).  `disable()` restores threading and returns
    the `Report`.
  - Product code names its hot locks through `tpu6824.utils.locks.
    new_lock/new_rlock(name=..., hold_budget_s=...)` — a zero-cost
    seam when the sanitizer is off, a labeled+budgeted watched lock
    when it is on.

Pure stdlib: importable (and testable) without JAX.
"""

from __future__ import annotations

import os
import threading
import time

_real_lock = threading.Lock
_real_rlock = threading.RLock

# Default hold budget applied when a named lock doesn't set one: generous
# enough that cold paths (checkpoint copies, first-dispatch staging) pass
# on a loaded CI box, tight enough to catch the ~160ms/retire class of
# regression (TUNING round 7).
DEFAULT_BUDGET_S = float(os.environ.get("TPU6824_LOCK_BUDGET", "0.25"))

_state_mu = _real_lock()  # guards the graph/violation structures below
_active = False
_edges: dict[tuple[int, int], dict] = {}    # (node_a, node_b) -> first-seen info
_nodes: dict[int, str] = {}                  # node id -> label
_violations: list[dict] = []
_order_violations: list[dict] = []
_MAX_VIOLATIONS = 256
# Canonical named-lock hierarchy (outermost first), pushed by
# tpu6824.utils.locks at import from its MANIFEST.  Acquiring a manifest
# lock while holding one that ranks BELOW it is an order violation even
# before any cycle closes — runtime lockdep against the same declaration
# the static consan pass validates.
_manifest_idx: dict[str, int] = {}
_serial = 0
_tls = threading.local()  # .held = [[node_id, t0, depth, label], ...]


def _held_stack() -> list:
    st = getattr(_tls, "held", None)
    if st is None:
        st = _tls.held = []
    return st


class Report:
    """What a sanitized run learned: the aggregated acquisition graph,
    any order cycles, and any hold-budget violations."""

    def __init__(self, nodes, edges, violations, order_violations=None):
        self.nodes = nodes          # node id -> label
        self.edges = edges          # (a, b) -> {"thread", "count"}
        self.violations = violations  # [{"lock", "held_s", "budget_s", ...}]
        # [{"held", "acquired", "held_rank", "acquired_rank", "thread"}]
        self.order_violations = order_violations or []

    def cycles(self) -> list[list[str]]:
        """Cycles in the lock acquisition graph, as label lists.  Node
        granularity is lock INSTANCES (two locks born at the same line
        are distinct nodes), so a reported cycle is a real ordering
        inversion, not a same-site alias."""
        succ: dict[int, list[int]] = {}
        for (a, b) in self.edges:
            succ.setdefault(a, []).append(b)
        WHITE, GREY, BLACK = 0, 1, 2
        color = dict.fromkeys(self.nodes, WHITE)
        out: list[list[str]] = []
        path: list[int] = []

        def dfs(n: int) -> None:
            color[n] = GREY
            path.append(n)
            for m in succ.get(n, ()):
                c = color.get(m, BLACK)
                if c == GREY:
                    i = path.index(m)
                    out.append([self.nodes[x] for x in path[i:]] +
                               [self.nodes[m]])
                elif c == WHITE:
                    dfs(m)
            path.pop()
            color[n] = BLACK

        for n in list(color):
            if color[n] == WHITE:
                dfs(n)
        return out

    def describe(self) -> str:
        lines = [f"lockwatch: {len(self.nodes)} locks, "
                 f"{len(self.edges)} order edges, "
                 f"{len(self.violations)} budget violations, "
                 f"{len(self.order_violations)} manifest-order violations"]
        for cyc in self.cycles():
            lines.append("  CYCLE: " + " -> ".join(cyc))
        for v in self.violations[:16]:
            lines.append(
                f"  HOLD {v['lock']}: {v['held_s'] * 1e3:.1f}ms "
                f"(budget {v['budget_s'] * 1e3:.0f}ms) at {v['site']}")
        for v in self.order_violations[:16]:
            lines.append(
                f"  ORDER {v['acquired']} (rank {v['acquired_rank']}) "
                f"acquired while holding {v['held']} (rank "
                f"{v['held_rank']}) on {v['thread']}")
        return "\n".join(lines)


class _Watched:
    """Instrumented lock wrapper.  Delegates to a real (R)Lock and keeps
    the per-thread held-set + global graph current.  Implements the
    `_release_save`/`_acquire_restore`/`_is_owned` trio so
    `threading.Condition` waits (which release and re-acquire out of
    band) keep the bookkeeping consistent."""

    __slots__ = ("_lk", "_node", "_label", "_budget", "_reentrant")

    def __init__(self, lk, node: int, label: str, budget: float | None,
                 reentrant: bool):
        self._lk = lk
        self._node = node
        self._label = label
        self._budget = budget
        self._reentrant = reentrant

    # -------------------------------------------------- bookkeeping

    def _note_acquired(self, ordered: bool = True) -> None:
        """`ordered=False` for bounded acquires (try-lock / timeout):
        they cannot participate in a hard deadlock — the acquirer backs
        off — so they contribute hold-time tracking but no order edge
        (shardkv's donor `mu.acquire(timeout=...)` pull is the canonical
        case: symmetric cross-group pulls LOOK like an inversion but
        resolve by timeout, per the module's divergence note)."""
        st = _held_stack()
        for ent in st:
            if ent[0] == self._node:
                ent[2] += 1  # reentrant re-acquire: no edge, no new timer
                return
        if _active and ordered:
            with _state_mu:
                for ent in st:
                    key = (ent[0], self._node)
                    e = _edges.get(key)
                    if e is None:
                        _edges[key] = {
                            "thread": threading.current_thread().name,
                            "count": 1,
                        }
                    else:
                        e["count"] += 1
                ni = _manifest_idx.get(self._label)
                if ni is not None:
                    for ent in st:
                        hi = _manifest_idx.get(ent[3])
                        if (hi is None or ni >= hi
                                or ent[3] == self._label):
                            continue
                        if len(_order_violations) < _MAX_VIOLATIONS and \
                                not any(v["held"] == ent[3]
                                        and v["acquired"] == self._label
                                        for v in _order_violations):
                            _order_violations.append({
                                "held": ent[3],
                                "acquired": self._label,
                                "held_rank": hi,
                                "acquired_rank": ni,
                                "thread":
                                    threading.current_thread().name,
                            })
        st.append([self._node, time.monotonic(), 1, self._label])

    def _note_released(self) -> None:
        st = _held_stack()
        for i in range(len(st) - 1, -1, -1):
            ent = st[i]
            if ent[0] != self._node:
                continue
            ent[2] -= 1
            if ent[2] == 0:
                held = time.monotonic() - ent[1]
                del st[i]
                if (_active and self._budget is not None
                        and held > self._budget):
                    import traceback

                    # Innermost frame that is NOT lockwatch itself: the
                    # releasing statement (a fixed index would point one
                    # frame off for direct .release() callers vs `with`).
                    site = "?"
                    for fr in reversed(traceback.extract_stack(limit=8)):
                        if "lockwatch" in fr.filename:
                            continue
                        site = f"{fr.filename}:{fr.lineno}"
                        break
                    with _state_mu:
                        if len(_violations) < _MAX_VIOLATIONS:
                            _violations.append({
                                "lock": self._label,
                                "held_s": held,
                                "budget_s": self._budget,
                                "thread": threading.current_thread().name,
                                "site": site,
                            })
            return

    # -------------------------------------------------- Lock protocol

    def acquire(self, *args, **kwargs):
        got = self._lk.acquire(*args, **kwargs)
        if got:
            blocking = args[0] if args else kwargs.get("blocking", True)
            timeout = (args[1] if len(args) > 1
                       else kwargs.get("timeout", -1))
            self._note_acquired(ordered=bool(blocking) and timeout == -1)
        return got

    def release(self):
        self._lk.release()
        self._note_released()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def locked(self):
        return self._lk.locked()

    # Condition-variable integration (threading.Condition duck-types
    # these off its lock; without them a cond.wait() would desync the
    # held-set).
    def _release_save(self):
        state = (self._lk._release_save() if hasattr(self._lk, "_release_save")
                 else self._lk.release())
        # wait(): the lock is fully released regardless of depth.
        st = _held_stack()
        for i in range(len(st) - 1, -1, -1):
            if st[i][0] == self._node:
                del st[i]
                break
        return state

    def _acquire_restore(self, state):
        if hasattr(self._lk, "_acquire_restore"):
            self._lk._acquire_restore(state)
        else:
            self._lk.acquire()
        self._note_acquired()

    def _is_owned(self):
        if hasattr(self._lk, "_is_owned"):
            return self._lk._is_owned()
        # Plain Lock: mimic threading.Condition's probe.
        if self._lk.acquire(False):
            self._lk.release()
            return False
        return True

    def __repr__(self):
        return f"<lockwatch {self._label} wrapping {self._lk!r}>"


def _creation_site() -> str:
    import traceback

    for fr in reversed(traceback.extract_stack(limit=8)[:-3]):
        fn = fr.filename
        if "lockwatch" in fn or fn.startswith("<"):
            continue
        if f"threading{os.sep}" in fn or fn.endswith("threading.py"):
            continue
        return f"{os.path.basename(fn)}:{fr.lineno}"
    return "?"


def _make(real_factory, reentrant: bool, name: str | None = None,
          hold_budget_s: float | None = None):
    global _serial
    label = name or f"lock@{_creation_site()}"
    with _state_mu:
        _serial += 1
        node = _serial
        _nodes[node] = label
    # Anonymous locks get no budget (short-held framework internals —
    # Event/Condition plumbing — would drown the report); named locks
    # default to DEFAULT_BUDGET_S.
    budget = hold_budget_s if (hold_budget_s is not None or name is None) \
        else DEFAULT_BUDGET_S
    return _Watched(real_factory(), node, label, budget, reentrant)


def _patched_lock():
    return _make(_real_lock, reentrant=False)


def _patched_rlock():
    return _make(_real_rlock, reentrant=True)


def set_manifest(names) -> None:
    """Declare the canonical named-lock hierarchy, outermost first
    (tpu6824.utils.locks.MANIFEST pushes itself here at import).  The
    declaration outlives enable/disable cycles: it is the contract, not
    a measurement."""
    with _state_mu:
        _manifest_idx.clear()
        _manifest_idx.update({n: i for i, n in enumerate(names)})


def manifest() -> tuple:
    with _state_mu:
        return tuple(sorted(_manifest_idx, key=_manifest_idx.get))


def enabled() -> bool:
    return _active


def enable() -> None:
    """Start sanitizing: locks created from now on are watched.  Clears
    any previous run's graph."""
    global _active
    with _state_mu:
        _edges.clear()
        _nodes.clear()
        _violations.clear()
        _order_violations.clear()
    _active = True
    threading.Lock = _patched_lock
    threading.RLock = _patched_rlock


def disable() -> Report:
    """Stop sanitizing, restore `threading`, and return the Report.
    Locks created while enabled keep working (they are plain wrappers)
    but stop recording."""
    global _active
    _active = False
    threading.Lock = _real_lock
    threading.RLock = _real_rlock
    with _state_mu:
        return Report(dict(_nodes), dict(_edges), list(_violations),
                      list(_order_violations))


def snapshot() -> Report:
    """Mid-run report (the sanitize fixture's failure path uses this to
    assert without tearing instrumentation down first)."""
    with _state_mu:
        return Report(dict(_nodes), dict(_edges), list(_violations),
                      list(_order_violations))


def make_lock(name: str | None = None, hold_budget_s: float | None = None):
    """A watched-if-sanitizing, plain-otherwise Lock.  Product code uses
    `tpu6824.utils.locks.new_lock`, which forwards here only when the
    sanitizer is active."""
    if not _active:
        return _real_lock()
    return _make(_real_lock, reentrant=False, name=name,
                 hold_budget_s=hold_budget_s)


def make_rlock(name: str | None = None, hold_budget_s: float | None = None):
    if not _active:
        return _real_rlock()
    return _make(_real_rlock, reentrant=True, name=name,
                 hold_budget_s=hold_budget_s)
