"""consan — whole-program interprocedural concurrency analysis.

tpusan's lint rules are file-local AST scans and lockwatch only sees the
interleavings a given run happened to take.  consan closes the gap
between them: ONE pass over the whole tree that

  - models thread entry points (the engine/driver/ticker daemons spawned
    through ``threading.Thread(target=crashsink.guarded(...))``, RPC
    handler registrations, pulse sampler hooks, the C++ event-loop
    callback seams) and propagates the thread class of each entry
    through a name-resolved call graph, so "which threads can run this
    method" is an analysis fact instead of a docstring convention;
  - extracts every lock acquisition (``with self.mu``, module-level
    locks, ``utils.locks.new_lock/new_rlock(name=...)`` named locks, the
    ``*_locked`` suffix and ``@_locked`` decorator conventions) and
    builds a STATIC lock-order graph — edge a→b means "some code path
    can acquire b while holding a", including paths that cross function
    and module boundaries — reporting cycles as deadlock potential even
    when no test interleaves them (``lock-order-cycle``);
  - validates the declared lock hierarchy: the canonical manifest in
    ``tpu6824.utils.locks.MANIFEST`` orders the named hot locks
    outermost→innermost; a static edge against that order is a
    ``lock-manifest-order`` finding, and a named lock missing from the
    manifest is ``lock-manifest-missing``.  lockwatch enforces the same
    manifest live (runtime lockdep), and ``merged_cycles`` unions the
    static graph with a lockwatch Report so the combined static ∪
    runtime graph is checked acyclic in tier-1;
  - flags lock-protection inconsistency (``unlocked-shared-state``): a
    ``self`` attribute written under the class lock in one method and
    touched lock-free from a method a DIFFERENT thread class can reach —
    exactly the PR 15 devapply mirror-cadence race shape;
  - flags blocking calls (sleep, socket I/O, device readback, ``.wait``)
    reachable while a lock is held INTERPROCEDURALLY
    (``lock-blocking-reachable``): the lexical rule catches ``with mu:
    sleep()``; this catches ``with mu: helper()`` where the sleep hides
    two calls down.

Precision stance: this is a linter, not a verifier.  Call resolution is
name-based and deliberately conservative — ``self.meth()`` resolves
within the class (and by-name bases), ``self.attr.meth()`` resolves
through ``self.attr = ClassName(...)`` assignments, module functions
resolve through the import map, and anything else is dropped rather
than over-approximated into noise.  Lock nodes are LABELS (one node per
named lock / per class attribute), so a same-label edge (two instances
of one class) is skipped: instance-level inversions of one class are
lockwatch's job, which keys by instance.  Findings suppress exactly
like tpusan's (``# tpusan: ok(<rule>) — why``), and suppressions
require the justification string — the loader rejects bare ones.

Pure stdlib (ast): no JAX import, fast enough for tier-1 (the analysis
test asserts a wall-clock budget over the full tree).
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field

from tpu6824.analysis.lint import (
    _BLOCKING_DOTTED,
    _BLOCKING_TAILS,
    WHOLE_PROGRAM_RULES,
    Finding,
    _collect_suppressions,
    _dotted,
    iter_py_files,
)

CONSAN_VERSION = "consan-1.0.0"

#: Rules this pass owns.  They live in lint.RULES (so the suppression
#: loader accepts them) but only consan can fire or clear them; lint's
#: per-file unused-suppression check defers them here.
CONSAN_RULES = WHOLE_PROGRAM_RULES

# Attribute names that read as "a lock" even without a visible decl
# (mirrors lint._LOCK_ATTRS plus the service-layer spellings).
_LOCKISH = {"mu", "emu", "_lock", "_mu", "_fs_lock", "_state_mu",
            "_mirror_mu", "_clock_mu", "_cseq_mu", "_wlock"}

# Constructors whose product is a thread-safe primitive: attributes
# assigned from these never trip unlocked-shared-state (their own
# synchronization is the point).
_SAFE_CTORS = {
    "threading.Lock", "threading.RLock", "threading.Event",
    "threading.Condition", "threading.Semaphore", "threading.Thread",
    "threading.local", "Lock", "RLock", "Event", "Condition",
    "new_lock", "new_rlock", "deque", "collections.deque", "Queue",
    "queue.Queue", "SimpleQueue",
}
_SAFE_CTOR_TAILS = {"Lock", "RLock", "Event", "Condition", "Thread",
                    "Semaphore", "counter", "gauge", "histogram",
                    "new_lock", "new_rlock", "deque", "Queue", "local"}

# Attribute mutators that count as writes for the shared-state rule.
_MUTATORS = {"append", "appendleft", "add", "extend", "insert",
             "setdefault", "update", "pop", "popitem", "popleft",
             "clear", "remove", "discard"}

# Methods whose bodies are lifecycle/bootstrap by convention: attribute
# traffic there predates (or postdates) concurrency.
_LIFECYCLE = {"__init__", "__new__", "__post_init__"}

# The repo-wide kill-flag convention: `self.dead` is a single-writer
# monotonic bool that daemon loops poll lock-free by design (the Go
# reference's `isdead()` atomic) — a torn read is impossible and a
# stale read only delays shutdown by one tick.
_KILL_FLAGS = {"dead", "_dead", "killed"}

# A justified lexical blocking suppression sanctions the blocking call
# for callers too: when the seed line carries an `ok(<one of these>)`
# suppression, lock-blocking-reachable does not re-fire the same
# decision at every call site up the graph.
_BLOCKING_SANCTION_RULES = {"lock-blocking-reachable", "lock-blocking-call",
                            "blocking-in-eventloop", "blocking-commit-wait"}

# Thread-class labels.
_TC_API = "api"
_TC_RPC = "rpc"
_TC_LOOP = "eventloop"
_TC_PULSE = "pulse"

_EVENTLOOP_FILES = ("services/frontend.py", "rpc/native_server.py")


# ------------------------------------------------------------ lock refs
# A lockref is a tuple:
#   ("attr", owner_key, attr)  — self/module lock, owner_key names the
#                                class ("mod:Cls") or module ("mod")
#   ("sym", param, attr)       — param-receiver lock (srv.mu), resolved
#                                at the call site when the caller passes
#                                self / its own param through


def _is_self_attr(node) -> str | None:
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None


@dataclass
class _LockDecl:
    label: str
    file: str
    line: int
    named: bool  # created via new_lock/new_rlock(name=...)


@dataclass
class _FuncInfo:
    key: str                      # "mod:Cls.meth" / "mod:func"
    module: str                   # module key ("services/kvpaxos")
    cls: str | None
    name: str
    file: str
    node: ast.AST = field(repr=False, default=None)
    params: list = field(default_factory=list)
    # events: ("acq", lockref, line) / ("call", site) / ("block", d, held, line)
    events: list = field(default_factory=list)
    accesses: list = field(default_factory=list)  # (attr, kind, locked, line)
    entry_tcs: set = field(default_factory=set)
    tcs: set = field(default_factory=set)
    initial_held: list = field(default_factory=list)  # lockrefs (conventions)


@dataclass
class _CallSite:
    callees: list                 # candidate _FuncInfo keys
    submap: dict                  # callee param name -> "self-cls:<key>"|("sym", p)
    held: list                    # lockrefs held lexically at the call
    line: int


class _ClassInfo:
    def __init__(self, module: str, name: str, file: str):
        self.module = module
        self.name = name
        self.key = f"{module}:{name}"
        self.file = file
        self.bases: list[str] = []
        self.locks: dict[str, _LockDecl] = {}   # attr -> decl
        self.safe_attrs: set[str] = set()
        self.attr_types: dict[str, str] = {}    # attr -> class name
        self.methods: dict[str, str] = {}       # meth name -> func key
        self.spawns_thread = False


class Program:
    """The parsed tree: modules, classes, functions, import maps."""

    def __init__(self):
        self.files: dict[str, str] = {}          # file -> source
        self.funcs: dict[str, _FuncInfo] = {}
        self.classes: dict[str, _ClassInfo] = {} # "mod:Cls" -> info
        self.by_method: dict[str, list[str]] = {}  # meth name -> func keys
        self.mod_funcs: dict[str, dict[str, str]] = {}  # mod -> name -> key
        self.mod_locks: dict[str, dict[str, _LockDecl]] = {}
        self.imports: dict[str, dict[str, str]] = {}  # mod -> alias -> modkey
        self.class_by_name: dict[str, list[str]] = {}
        self.decorator_locks: dict[str, str] = {}  # "mod:decname" -> attr
        self.sups: dict[str, dict] = {}          # file -> line -> Suppression


def _match_suppression(prog: Program, path: str, line: int,
                       rules: set) -> object | None:
    """The tpusan matching walk: a suppression on `line`, or in the
    comment block directly above it, covering any of `rules`."""
    sups = prog.sups.get(path)
    if not sups:
        return None
    src = prog.files.get(path, "")
    lines = src.splitlines()

    def comment_only(ln: int) -> bool:
        return 1 <= ln <= len(lines) and \
            lines[ln - 1].lstrip().startswith("#")

    candidates = [line]
    ln = line - 1
    while comment_only(ln):
        candidates.append(ln)
        if ln in sups:
            break
        ln -= 1
    candidates.append(ln)
    for ln in candidates:
        s = sups.get(ln)
        if s and ("*" in s.rules or (s.rules & rules)):
            return s
    return None


def _mod_key(relpath: str) -> str:
    p = relpath.replace(os.sep, "/")
    for marker in ("tpu6824/",):
        i = p.find(marker)
        if i >= 0:
            p = p[i + len(marker):]
            break
    return p[:-3] if p.endswith(".py") else p


def _lock_ctor(value: ast.AST) -> tuple[bool, str | None] | None:
    """(named, name) when `value` constructs a lock, else None."""
    if not isinstance(value, ast.Call):
        return None
    d = _dotted(value.func) or ""
    tail = d.rsplit(".", 1)[-1]
    if tail in ("new_lock", "new_rlock", "make_lock", "make_rlock"):
        name = None
        if value.args and isinstance(value.args[0], ast.Constant) and \
                isinstance(value.args[0].value, str):
            name = value.args[0].value
        for kw in value.keywords:
            if kw.arg == "name" and isinstance(kw.value, ast.Constant):
                name = kw.value.value
        return (name is not None, name)
    if d in ("threading.Lock", "threading.RLock") or \
            (tail in ("Lock", "RLock") and "." not in d):
        return (False, None)
    return None


def _is_safe_ctor(value: ast.AST) -> bool:
    if not isinstance(value, ast.Call):
        return False
    d = _dotted(value.func) or ""
    return d in _SAFE_CTORS or d.rsplit(".", 1)[-1] in _SAFE_CTOR_TAILS


# ------------------------------------------------------------ indexing


def _index_module(prog: Program, path: str, relpath: str,
                  tree: ast.Module) -> None:
    mod = _mod_key(relpath)
    prog.mod_funcs.setdefault(mod, {})
    prog.mod_locks.setdefault(mod, {})
    prog.imports.setdefault(mod, {})

    for node in tree.body:
        if isinstance(node, ast.Import):
            for a in node.names:
                prog.imports[mod][a.asname or a.name.split(".")[0]] = \
                    a.name.replace(".", "/")
        elif isinstance(node, ast.ImportFrom) and node.module:
            src = node.module.replace(".", "/")
            for a in node.names:
                prog.imports[mod][a.asname or a.name] = f"{src}#{a.name}"
        elif isinstance(node, ast.Assign):
            ctor = _lock_ctor(node.value)
            if ctor is not None:
                named, name = ctor
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        prog.mod_locks[mod][t.id] = _LockDecl(
                            name or f"{mod}.{t.id}", path, node.lineno,
                            named)
        elif isinstance(node, ast.FunctionDef):
            key = f"{mod}:{node.name}"
            prog.mod_funcs[mod][node.name] = key
            prog.funcs[key] = _FuncInfo(
                key, mod, None, node.name, path, node,
                [a.arg for a in node.args.args])
            attr = _decorator_lock_attr(node)
            if attr:
                prog.decorator_locks[f"{mod}:{node.name}"] = attr
        elif isinstance(node, ast.ClassDef):
            _index_class(prog, mod, path, node)


def _decorator_lock_attr(fn: ast.FunctionDef) -> str | None:
    """A decorator whose nested wrapper runs the wrapped call inside
    `with self.<attr>` (the devapply `_locked` shape) hands that lock to
    every method it decorates."""
    for n in ast.walk(fn):
        if isinstance(n, ast.FunctionDef) and n is not fn:
            for m in ast.walk(n):
                if isinstance(m, ast.With):
                    for item in m.items:
                        a = _is_self_attr(item.context_expr)
                        if a:
                            return a
    return None


def _index_class(prog: Program, mod: str, path: str,
                 node: ast.ClassDef) -> None:
    ci = _ClassInfo(mod, node.name, path)
    for b in node.bases:
        d = _dotted(b)
        if d:
            ci.bases.append(d.rsplit(".", 1)[-1])
    prog.classes[ci.key] = ci
    prog.class_by_name.setdefault(node.name, []).append(ci.key)
    for item in node.body:
        if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
            key = f"{mod}:{node.name}.{item.name}"
            ci.methods[item.name] = key
            prog.funcs[key] = _FuncInfo(
                key, mod, node.name, item.name, path, item,
                [a.arg for a in item.args.args])
            prog.by_method.setdefault(item.name, []).append(key)
    # attribute decls: lock attrs, safe attrs, typed attrs — anywhere in
    # the class body (locks are born in __init__ by convention, but
    # enable_ingest-style lazy inits exist).
    for n in ast.walk(node):
        if not isinstance(n, ast.Assign):
            continue
        for t in n.targets:
            attr = _is_self_attr(t)
            if attr is None:
                continue
            ctor = _lock_ctor(n.value)
            if ctor is not None:
                named, name = ctor
                ci.locks[attr] = _LockDecl(
                    name or f"{ci.key}.{attr}", path, n.lineno, named)
                ci.safe_attrs.add(attr)
                continue
            if _is_safe_ctor(n.value):
                ci.safe_attrs.add(attr)
            if isinstance(n.value, ast.Call):
                d = _dotted(n.value.func)
                if d:
                    cname = d.rsplit(".", 1)[-1]
                    if cname in prog.class_by_name or cname[:1].isupper():
                        ci.attr_types.setdefault(attr, cname)


# ------------------------------------------------------ event extraction


class _Extractor:
    """Per-function event walk: lock regions (`with`), calls with their
    held-stack, blocking calls, attribute accesses.  Nested defs are
    skipped (a closure handed elsewhere runs elsewhere)."""

    def __init__(self, prog: Program, fi: _FuncInfo):
        self.prog = prog
        self.fi = fi
        self.mod = fi.module
        self.ci = prog.classes.get(f"{fi.module}:{fi.cls}") if fi.cls \
            else None
        self.alias: dict[str, str] = {}  # local -> self attr (lk = self.mu)

    # ---- lockref resolution

    def _lockref(self, expr: ast.AST):
        attr = _is_self_attr(expr)
        if attr is not None:
            if (self.ci and attr in self.ci.locks) or attr in _LOCKISH \
                    or attr.endswith(("_mu", "_lock")):
                owner = self._lock_owner(attr)
                return ("attr", owner, attr)
            return None
        if isinstance(expr, ast.Name):
            if expr.id in self.alias:
                return self._lockref_attr(self.alias[expr.id])
            if expr.id in self.prog.mod_locks.get(self.mod, {}):
                return ("attr", self.mod, expr.id)
            return None
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name):
            base, attr = expr.value.id, expr.attr
            if base in self.fi.params and (
                    attr in _LOCKISH or attr.endswith(("_mu", "_lock"))):
                return ("sym", base, attr)
            # module-level lock through an import alias
            tgt = self.prog.imports.get(self.mod, {}).get(base)
            if tgt and "#" not in tgt and \
                    attr in self.prog.mod_locks.get(tgt, {}):
                return ("attr", tgt, attr)
        return None

    def _lockref_attr(self, attr: str):
        return ("attr", self._lock_owner(attr), attr)

    def _lock_owner(self, attr: str) -> str:
        """The class key whose decl wins for `self.attr` — the defining
        base if the using class doesn't declare it."""
        if self.ci is None:
            return self.mod
        if attr in self.ci.locks:
            return self.ci.key
        for b in self.ci.bases:
            for bk in self.prog.class_by_name.get(b, ()):
                bci = self.prog.classes[bk]
                if attr in bci.locks:
                    return bk
        return self.ci.key

    # ---- call resolution

    def _callees(self, call: ast.Call) -> list[str]:
        f = call.func
        d = _dotted(f)
        if d is None:
            return []
        parts = d.split(".")
        # self.meth(...)
        if len(parts) == 2 and parts[0] == "self" and self.ci:
            m = self._class_method(self.ci, parts[1])
            return [m] if m else []
        # self.attr.meth(...) through a typed attribute
        if len(parts) == 3 and parts[0] == "self" and self.ci:
            tname = self.ci.attr_types.get(parts[1])
            if tname:
                for ck in self.prog.class_by_name.get(tname, ()):
                    m = self._class_method(self.prog.classes[ck], parts[2])
                    if m:
                        return [m]
            return []
        # bare func(...)
        if len(parts) == 1:
            k = self.prog.mod_funcs.get(self.mod, {}).get(parts[0])
            if k:
                return [k]
            tgt = self.prog.imports.get(self.mod, {}).get(parts[0])
            if tgt and "#" in tgt:
                m, fn = tgt.split("#")
                k = self.prog.mod_funcs.get(m, {}).get(fn)
                return [k] if k else []
            return []
        # mod.func(...)
        if len(parts) == 2:
            tgt = self.prog.imports.get(self.mod, {}).get(parts[0])
            if tgt and "#" not in tgt:
                k = self.prog.mod_funcs.get(tgt, {}).get(parts[1])
                return [k] if k else []
        return []

    def _class_method(self, ci: _ClassInfo, name: str) -> str | None:
        if name in ci.methods:
            return ci.methods[name]
        for b in ci.bases:
            for bk in self.prog.class_by_name.get(b, ()):
                m = self._class_method(self.prog.classes[bk], name)
                if m:
                    return m
        return None

    def _submap(self, call: ast.Call, callee_key: str) -> dict:
        """callee param -> caller base, for symbolic lock substitution.
        Bases: "cls:<classkey>" (caller passed self) or ("sym", p)
        (caller passed its own param through)."""
        fi = self.prog.funcs.get(callee_key)
        if fi is None:
            return {}
        params = list(fi.params)
        sub: dict = {}
        if fi.cls is not None and params and params[0] == "self":
            # bound call: self maps to the callee's own class
            sub["self"] = f"cls:{fi.module}:{fi.cls}"
            params = params[1:]
        for p, a in zip(params, call.args):
            if isinstance(a, ast.Name):
                if a.id == "self" and self.ci:
                    sub[p] = f"cls:{self.ci.key}"
                elif a.id in self.fi.params:
                    sub[p] = ("sym", a.id)
        return sub

    # ---- the walk

    def run(self) -> None:
        fi = self.fi
        node = fi.node
        # held-by-convention: the *_locked suffix (caller already holds
        # the server lock) and `_apply*` (the RSM apply path, entered
        # only from the decided drain under mu — same convention lint's
        # blocking-commit-wait encodes) / lock-wrapping decorator
        if fi.cls and (fi.name.endswith("_locked")
                       or fi.name.startswith("_apply")) and self.ci:
            primary = self._primary_lock()
            if primary:
                fi.initial_held.append(primary)
        for dec in getattr(node, "decorator_list", ()):
            d = _dotted(dec)
            if d:
                attr = self.prog.decorator_locks.get(
                    f"{fi.module}:{d.rsplit('.', 1)[-1]}")
                if attr:
                    ref = self._lockref_attr(attr)
                    fi.initial_held.append(ref)
                    # Unlike *_locked (caller already holds), a lock-
                    # wrapping decorator ACQUIRES — a caller holding mu
                    # who calls a decorated method takes emu through
                    # it, so the edge must be visible to callers.
                    fi.events.append(("acq", ref, [], node.lineno))
        # alias prescan: lk = self.mu
        for n in ast.walk(node):
            if isinstance(n, ast.Assign) and len(n.targets) == 1 and \
                    isinstance(n.targets[0], ast.Name):
                a = _is_self_attr(n.value)
                if a and ((self.ci and a in self.ci.locks)
                          or a in _LOCKISH):
                    self.alias[n.targets[0].id] = a
        self._walk_body(list(node.body), list(fi.initial_held))

    def _primary_lock(self):
        for cand in ("mu", "_lock", "_mu", "_fs_lock", "emu"):
            if self.ci and cand in self.ci.locks:
                return ("attr", self.ci.key, cand)
            if cand in _LOCKISH and self.ci:
                # undeclared (inherited) primary: resolve through bases
                for b in self.ci.bases:
                    for bk in self.prog.class_by_name.get(b, ()):
                        if cand in self.prog.classes[bk].locks:
                            return ("attr", bk, cand)
        return None

    def _walk_body(self, stmts: list, held: list) -> None:
        for st in stmts:
            self._walk_stmt(st, held)

    def _walk_stmt(self, st: ast.AST, held: list) -> None:
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return  # nested def: runs elsewhere
        if isinstance(st, ast.With):
            acquired = []
            for item in st.items:
                ref = self._lockref(item.context_expr)
                if ref is not None and ref not in held:
                    self.fi.events.append(("acq", ref, list(held),
                                           st.lineno))
                    acquired.append(ref)
            self._walk_body(st.body, held + acquired)
            return
        if isinstance(st, ast.Try):
            # Manual lock discipline: a `try:` whose `finally:` calls
            # `X.release()` runs its body HELD (diskv.full_snapshot's
            # timeout-acquire shape).  Held matters for access
            # classification, blocking reach and outbound edges; the
            # try-acquire itself contributes no inbound order edge —
            # same stance as lockwatch's ordered=False for
            # timeout/try acquires, which cannot wedge a cycle.
            rel = self._finally_released(st)
            if rel is not None and rel not in held:
                self._walk_body(st.body, held + [rel])
                for h in st.handlers:
                    self._walk_body(h.body, held + [rel])
                self._walk_body(st.orelse, held + [rel])
                self._walk_body(st.finalbody, held)
                return
        for attr, kind, line in self._attr_traffic(st):
            self.fi.accesses.append((attr, kind, bool(held), line))
        for call in self._calls_of(st):
            d = _dotted(call.func)
            if d is not None:
                tail = d.rsplit(".", 1)[-1]
                if d in _BLOCKING_DOTTED or (
                        "." in d and tail in _BLOCKING_TAILS):
                    self.fi.events.append(("block", d, list(held),
                                           call.lineno))
            callees = self._callees(call)
            if callees:
                self.fi.events.append(("call", _CallSite(
                    callees,
                    {k: self._submap(call, k) for k in callees},
                    list(held), call.lineno)))
        # recurse into compound statements (their nested stmts share the
        # held stack); With handled above, defs skipped.  ExceptHandler
        # is not an ast.stmt but carries a stmt body.
        for child in ast.iter_child_nodes(st):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                continue
            if isinstance(child, (ast.stmt, ast.ExceptHandler)):
                self._walk_stmt(child, held)

    def _finally_released(self, st: ast.Try):
        """The lockref a `finally:` block releases, if any."""
        for fin in st.finalbody:
            for call in self._calls_of(fin):
                f = call.func
                if isinstance(f, ast.Attribute) and f.attr == "release":
                    ref = self._lockref(f.value)
                    if ref is not None:
                        return ref
        return None

    def _calls_of(self, st: ast.AST):
        """Calls lexically in `st` but not inside a nested stmt (those
        are visited by the recursion) or nested def."""
        out = []
        for n in self._shallow_walk(st):
            if isinstance(n, ast.Call):
                out.append(n)
        return out

    def _attr_traffic(self, st: ast.AST):
        out = []
        if isinstance(st, ast.Assign):
            for t in st.targets:
                a = _is_self_attr(t)
                if a:
                    out.append((a, "w", st.lineno))
                elif isinstance(t, ast.Subscript):
                    a = _is_self_attr(t.value)
                    if a:
                        out.append((a, "w", st.lineno))
        elif isinstance(st, ast.AugAssign):
            a = _is_self_attr(st.target)
            if a:
                out.append((a, "w", st.lineno))
        elif isinstance(st, ast.Delete):
            for t in st.targets:
                if isinstance(t, ast.Subscript):
                    a = _is_self_attr(t.value)
                    if a:
                        out.append((a, "w", st.lineno))
        for n in self._shallow_walk(st):
            if isinstance(n, ast.Call) and \
                    isinstance(n.func, ast.Attribute) and \
                    n.func.attr in _MUTATORS:
                a = _is_self_attr(n.func.value)
                if a:
                    out.append((a, "w", n.lineno))
            elif isinstance(n, ast.Attribute) and \
                    isinstance(n.ctx, ast.Load):
                a = _is_self_attr(n)
                if a:
                    out.append((a, "r", n.lineno))
        return out

    def _shallow_walk(self, st: ast.AST):
        """Expression-level walk of ONE statement: stops at nested
        statements / handlers (recursed separately) and nested defs."""
        todo = [c for c in ast.iter_child_nodes(st)
                if not isinstance(c, (ast.stmt, ast.ExceptHandler))]
        seen = []
        while todo:
            n = todo.pop()
            if isinstance(n, (ast.stmt, ast.ExceptHandler, ast.Lambda,
                              ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            seen.append(n)
            todo.extend(ast.iter_child_nodes(n))
        return seen


# ------------------------------------------------------------ entries


def _detect_entries(prog: Program) -> None:
    """Thread entry points, attached to _FuncInfo.entry_tcs."""
    for key, fi in prog.funcs.items():
        rel = fi.file.replace(os.sep, "/")
        # C++ event-loop callback seams
        if any(rel.endswith(s) for s in _EVENTLOOP_FILES) and (
                fi.name.startswith("_on_") or fi.name.endswith("_cb")):
            fi.entry_tcs.add(_TC_LOOP)
        # public service methods: callable from any client thread
        if fi.cls is not None and not fi.name.startswith("_") and any(
                seg in rel for seg in ("/services/", "/rpc/", "/core/",
                                       "/obs/", "/harness/")):
            fi.entry_tcs.add(_TC_API)

    for key, fi in list(prog.funcs.items()):
        if fi.node is None:
            continue
        ci = prog.classes.get(f"{fi.module}:{fi.cls}") if fi.cls else None
        for n in ast.walk(fi.node):
            if not isinstance(n, ast.Call):
                continue
            d = _dotted(n.func) or ""
            tail = d.rsplit(".", 1)[-1]
            if tail == "Thread":
                _mark_thread_target(prog, fi, ci, n)
            elif tail in ("register", "register_inline"):
                if len(n.args) >= 2:
                    _mark_entry(prog, fi, ci, n.args[1], _TC_RPC)
            elif tail == "register_obj" and n.args:
                a = n.args[0]
                if isinstance(a, ast.Name) and a.id == "self" and ci:
                    for m, mk in ci.methods.items():
                        if not m.startswith("_"):
                            prog.funcs[mk].entry_tcs.add(_TC_RPC)
            elif tail in ("add_global_sampler", "register_tracker",
                          "add_sampler", "add_observer"):
                for a in n.args:
                    _mark_entry(prog, fi, ci, a, _TC_PULSE)


def _mark_thread_target(prog, fi, ci, call: ast.Call) -> None:
    target = next((kw.value for kw in call.keywords
                   if kw.arg == "target"), None)
    if target is None:
        return
    label = None
    if isinstance(target, ast.Call):
        d = _dotted(target.func) or ""
        if d.endswith("guarded") and target.args:
            if len(target.args) > 1 and \
                    isinstance(target.args[1], ast.Constant):
                label = str(target.args[1].value)
            target = target.args[0]
        else:
            return
    if ci:
        ci.spawns_thread = True
    _mark_entry(prog, fi, ci, target, label or "thread")


def _mark_entry(prog, fi, ci, expr, tc: str) -> None:
    a = _is_self_attr(expr)
    if a is not None and ci:
        mk = ci.methods.get(a)
        if mk:
            prog.funcs[mk].entry_tcs.add(tc)
        return
    if isinstance(expr, ast.Name):
        k = prog.mod_funcs.get(fi.module, {}).get(expr.id)
        if k:
            prog.funcs[k].entry_tcs.add(tc)


def _propagate_tcs(prog: Program) -> None:
    """BFS each entry's thread class through the call graph."""
    succ: dict[str, set[str]] = {}
    for key, fi in prog.funcs.items():
        outs = set()
        for ev in fi.events:
            if ev[0] == "call":
                outs.update(ev[1].callees)
        succ[key] = outs
    work = []
    for key, fi in prog.funcs.items():
        if fi.entry_tcs:
            fi.tcs |= fi.entry_tcs
            work.append(key)
    while work:
        key = work.pop()
        tcs = prog.funcs[key].tcs
        for nxt in succ.get(key, ()):
            nfi = prog.funcs.get(nxt)
            if nfi is None:
                continue
            if not tcs <= nfi.tcs:
                nfi.tcs |= tcs
                work.append(nxt)


def _locked_ctx(prog: Program) -> set[str]:
    """Methods that run under their class lock WITHOUT taking it —
    the interprocedural half of the *_locked convention: every visible
    call site either holds a lock lexically or sits in a method already
    known to run locked.  Entry points (thread targets, RPC handlers,
    public API) never qualify: they are called from outside with
    nothing held."""
    ctx = {k for k, fi in prog.funcs.items() if fi.cls and fi.initial_held}
    # callee -> [(caller_key, lexically_held_at_site)]
    sites: dict[str, list] = {}
    for key, fi in prog.funcs.items():
        for ev in fi.events:
            if ev[0] != "call":
                continue
            for ck in ev[1].callees:
                sites.setdefault(ck, []).append((key, bool(ev[1].held)))
    for _ in range(12):
        changed = False
        for key, fi in prog.funcs.items():
            if key in ctx or fi.cls is None or fi.entry_tcs:
                continue
            ss = sites.get(key)
            if not ss:
                continue
            if all(held or caller in ctx for caller, held in ss):
                ctx.add(key)
                changed = True
        if not changed:
            break
    return ctx


# ------------------------------------------------------ lock summaries


def _subst(ref, submap: dict):
    """Resolve a symbolic lockref through a call edge's submap."""
    if ref[0] != "sym":
        return ref
    base = submap.get(ref[1])
    if base is None:
        return None
    if isinstance(base, str) and base.startswith("cls:"):
        return ("attr", base[4:], ref[2])
    if isinstance(base, tuple) and base[0] == "sym":
        return ("sym", base[1], ref[2])
    return None


def _fix_acquires(prog: Program) -> dict[str, set]:
    """Fixpoint: every lockref a function may acquire, transitively."""
    acq: dict[str, set] = {k: set() for k in prog.funcs}
    for key, fi in prog.funcs.items():
        for ev in fi.events:
            if ev[0] == "acq":
                acq[key].add(ev[1])
    for _ in range(24):
        changed = False
        for key, fi in prog.funcs.items():
            cur = acq[key]
            before = len(cur)
            for ev in fi.events:
                if ev[0] != "call":
                    continue
                site = ev[1]
                for ck in site.callees:
                    for ref in acq.get(ck, ()):
                        r = _subst(ref, site.submap.get(ck, {}))
                        if r is not None:
                            cur.add(r)
            if len(cur) != before:
                changed = True
        if not changed:
            break
    return acq


def _fix_blocking(prog: Program) -> dict[str, set]:
    """Fixpoint: blocking calls reachable from each function when it
    does NOT guard them behind its own lock... conservative: any
    blocking call in the body (lexical `held` there is the callee's
    business) propagates up with a chain tag."""
    blk: dict[str, set] = {k: set() for k in prog.funcs}
    for key, fi in prog.funcs.items():
        for ev in fi.events:
            if ev[0] == "block":
                s = _match_suppression(prog, fi.file, ev[3],
                                       _BLOCKING_SANCTION_RULES)
                if s is not None:
                    # A justified lexical suppression sanctions callers
                    # too — don't re-litigate it up the call graph.
                    if s.rules <= set(CONSAN_RULES):
                        s.used = True  # consan-owned: we account for it
                    continue
                blk[key].add((ev[1], f"{fi.name}:{ev[3]}"))
    for _ in range(24):
        changed = False
        for key, fi in prog.funcs.items():
            cur = blk[key]
            before = len(cur)
            for ev in fi.events:
                if ev[0] != "call":
                    continue
                for ck in ev[1].callees:
                    for d, chain in blk.get(ck, ()):
                        cfi = prog.funcs.get(ck)
                        tag = f"{cfi.name}->{chain}" if cfi else chain
                        if len(tag) < 200:
                            cur.add((d, tag))
            if len(cur) != before:
                changed = True
        if not changed:
            break
    return blk


def _label(prog: Program, ref) -> str | None:
    if ref[0] != "attr":
        return None
    _, owner, attr = ref
    ci = prog.classes.get(owner)
    if ci is not None:
        decl = ci.locks.get(attr)
        if decl is not None:
            return decl.label
        return f"{owner}.{attr}"
    decl = prog.mod_locks.get(owner, {}).get(attr)
    if decl is not None:
        return decl.label
    return f"{owner}.{attr}"


# ------------------------------------------------------------ analysis


class Analysis:
    """The whole-program result: findings + the static lock-order graph
    (label-keyed edges with first-seen provenance)."""

    def __init__(self):
        self.findings: list[Finding] = []
        self.edges: dict[tuple[str, str], dict] = {}
        self.named_locks: dict[str, _LockDecl] = {}
        self.nfiles = 0

    def cycles(self) -> list[list[str]]:
        succ: dict[str, list[str]] = {}
        for (a, b) in self.edges:
            succ.setdefault(a, []).append(b)
        WHITE, GREY, BLACK = 0, 1, 2
        nodes = {n for e in self.edges for n in e}
        color = dict.fromkeys(nodes, WHITE)
        out: list[list[str]] = []
        path: list[str] = []

        def dfs(n: str) -> None:
            color[n] = GREY
            path.append(n)
            for m in succ.get(n, ()):
                c = color.get(m, BLACK)
                if c == GREY:
                    i = path.index(m)
                    out.append(path[i:] + [m])
                elif c == WHITE:
                    dfs(m)
            path.pop()
            color[n] = BLACK

        for n in sorted(nodes):
            if color[n] == WHITE:
                dfs(n)
        return out

    def edge_list(self) -> list[dict]:
        return [{"from": a, "to": b, **info}
                for (a, b), info in sorted(self.edges.items())]


def merged_cycles(analysis: "Analysis", report) -> list[list[str]]:
    """Cycles of the UNION of the static graph and a lockwatch Report's
    runtime graph (label granularity).  Static sees orders no run took;
    runtime sees instance-level and dynamic orders the static resolver
    dropped — the merged graph must stay acyclic for the hierarchy to
    be real."""
    edges = set(analysis.edges)
    for (a, b) in report.edges:
        la, lb = report.nodes.get(a), report.nodes.get(b)
        if la and lb and la != lb:
            edges.add((la, lb))
    merged = Analysis()
    merged.edges = {e: {} for e in edges}
    return merged.cycles()


def analyze_paths(paths: list[str], manifest=None) -> Analysis:
    """Run consan over a file/directory set.  `manifest` defaults to
    the canonical tpu6824.utils.locks.MANIFEST."""
    if manifest is None:
        from tpu6824.utils.locks import MANIFEST as manifest  # noqa: N811
    prog = Program()
    res = Analysis()
    for f in iter_py_files(paths):
        try:
            with open(f, encoding="utf-8") as fh:
                src = fh.read()
            tree = ast.parse(src, filename=f)
        except (OSError, SyntaxError):
            continue
        rel = f.replace(os.sep, "/")
        prog.files[f] = src
        prog.sups[f] = _collect_suppressions(src, f, [])
        res.nfiles += 1
        _index_module(prog, f, rel, tree)
    for fi in prog.funcs.values():
        _Extractor(prog, fi).run()
    _detect_entries(prog)
    _propagate_tcs(prog)
    acq = _fix_acquires(prog)

    # ---- static lock-order edges
    for key, fi in prog.funcs.items():
        for ev in fi.events:
            if ev[0] == "acq":
                _, ref, held, line = ev
                la = _label(prog, ref)
                if la is None:
                    continue
                for h in held:
                    lh = _label(prog, h)
                    if lh and lh != la:
                        res.edges.setdefault((lh, la), {
                            "file": fi.file, "line": line,
                            "via": fi.key})
            elif ev[0] == "call":
                site = ev[1]
                if not site.held:
                    continue
                for ck in site.callees:
                    for ref in acq.get(ck, ()):
                        r = _subst(ref, site.submap.get(ck, {}))
                        if r is None:
                            continue
                        la = _label(prog, r)
                        if la is None:
                            continue
                        for h in site.held:
                            lh = _label(prog, h)
                            if lh and lh != la:
                                res.edges.setdefault((lh, la), {
                                    "file": fi.file, "line": site.line,
                                    "via": f"{fi.key}->{ck}"})

    # named-lock inventory
    for ci in prog.classes.values():
        for decl in ci.locks.values():
            if decl.named:
                res.named_locks.setdefault(decl.label, decl)
    for mod, locks in prog.mod_locks.items():
        for decl in locks.values():
            if decl.named:
                res.named_locks.setdefault(decl.label, decl)

    ctx = _locked_ctx(prog)
    _check_cycles(prog, res)
    _check_manifest(prog, res, manifest)
    _check_shared_state(prog, res, ctx)
    _check_blocking_reachable(prog, res)
    _apply_suppressions(prog, res)
    return res


def _check_cycles(prog: Program, res: Analysis) -> None:
    for cyc in res.cycles():
        # anchor at the provenance of the cycle's first edge
        info = res.edges.get((cyc[0], cyc[1])) or {}
        res.findings.append(Finding(
            info.get("file", "?"), info.get("line", 0),
            "lock-order-cycle",
            "static lock-order cycle: " + " -> ".join(cyc) +
            f" (first edge via {info.get('via', '?')})"))


def _check_manifest(prog: Program, res: Analysis, manifest) -> None:
    idx = {name: i for i, name in enumerate(manifest)}
    for label, decl in sorted(res.named_locks.items()):
        if label not in idx:
            res.findings.append(Finding(
                decl.file, decl.line, "lock-manifest-missing",
                f"named lock {label!r} is not declared in "
                "tpu6824.utils.locks.MANIFEST — add it at its rank in "
                "the canonical acquisition order"))
    for (a, b), info in sorted(res.edges.items()):
        ia, ib = idx.get(a), idx.get(b)
        if ia is not None and ib is not None and ib < ia:
            res.findings.append(Finding(
                info["file"], info["line"], "lock-manifest-order",
                f"acquisition edge {a} -> {b} inverts the declared "
                f"manifest order (rank {ia} -> {ib}) via {info['via']}"))


def _check_shared_state(prog: Program, res: Analysis,
                        ctx: set) -> None:
    for ck, ci in prog.classes.items():
        if not ci.locks:
            continue
        tcs_union: set = set()
        for mk in ci.methods.values():
            tcs_union |= prog.funcs[mk].tcs
        if not ci.spawns_thread and len(tcs_union) < 2:
            continue
        writes: dict[str, tuple] = {}   # attr -> (fi, line) locked write
        bare: dict[str, list] = {}      # attr -> [(fi, line, kind)]
        for mname, mk in ci.methods.items():
            fi = prog.funcs[mk]
            if mname in _LIFECYCLE:
                continue
            in_ctx = mk in ctx
            for attr, kind, locked, line in fi.accesses:
                if attr in ci.safe_attrs or attr in _LOCKISH or \
                        attr in _KILL_FLAGS or \
                        attr.endswith(("_mu", "_lock")):
                    continue
                if (locked or in_ctx) and kind == "w":
                    if attr not in writes:
                        writes[attr] = (fi, line)
                elif not locked and not in_ctx:
                    bare.setdefault(attr, []).append((fi, line, kind))
        for attr, (wfi, wline) in sorted(writes.items()):
            sites = bare.get(attr)
            if not sites:
                continue
            for bfi, bline, kind in sites:
                if bfi.key == wfi.key:
                    continue
                # cross-thread evidence: the bare site's thread classes
                # must not be a subset of the locked writer's (same-
                # thread traffic is the lock's own business)
                if not bfi.tcs or bfi.tcs <= wfi.tcs:
                    continue
                res.findings.append(Finding(
                    bfi.file, bline, "unlocked-shared-state",
                    f"self.{attr} ({'write' if kind == 'w' else 'read'} "
                    f"in {ci.name}.{bfi.name}, threads "
                    f"{'/'.join(sorted(bfi.tcs))}) touched lock-free "
                    f"but written under the lock in {ci.name}."
                    f"{wfi.name} ({wfi.file.rsplit('/', 1)[-1]}:{wline}"
                    f", threads {'/'.join(sorted(wfi.tcs)) or '-'})"))
                break  # one finding per (class, attr)


def _check_blocking_reachable(prog: Program, res: Analysis) -> None:
    blk = _fix_blocking(prog)
    for key, fi in prog.funcs.items():
        for ev in fi.events:
            if ev[0] != "call" or not ev[1].held:
                continue
            site = ev[1]
            held_labels = [x for x in (_label(prog, h) for h in site.held)
                           if x]
            if not held_labels:
                continue
            for ck in site.callees:
                hits = blk.get(ck, ())
                if not hits:
                    continue
                d, chain = sorted(hits)[0]
                res.findings.append(Finding(
                    fi.file, site.line, "lock-blocking-reachable",
                    f"holding {'/'.join(held_labels)}, call into "
                    f"{prog.funcs[ck].name}() reaches blocking "
                    f"{d}() (chain {chain}) — the lock stalls every "
                    "waiter for the full blocking call"))
                break  # one finding per call site


def _apply_suppressions(prog: Program, res: Analysis) -> None:
    """tpusan-style suppression matching against the shared per-file
    suppression tables, plus consan-owned unused-suppression reporting
    (only for suppressions whose rules are ALL consan rules — mixed
    ones are the lint pass's to account for)."""
    for f in res.findings:
        s = _match_suppression(prog, f.path, f.line, {f.rule})
        if s is not None:
            f.suppressed = True
            s.used = True
    extra: list[Finding] = []
    for path, sups in prog.sups.items():
        for s in sups.values():
            if not s.used and s.rules and s.rules <= set(CONSAN_RULES):
                extra.append(Finding(
                    path, s.line, "unused-suppression",
                    f"consan suppression for {sorted(s.rules)} matches "
                    "no finding"))
    res.findings.extend(extra)
