"""jitguard — JAX recompile / tracer-hygiene guard.

The fabric's whole performance story rests on a FIXED set of compiled
programs: injection buckets are padded to two fixed sizes, the fused
step jit has exactly one signature per (reliable?) variant, and the
clock re-dispatches the same executable forever.  An unexpected
recompile in steady state means someone broke that contract (a shape
that varies per batch, a new static arg, a Python float sneaking into a
traced position) — on TPU that is a multi-second stall per occurrence,
invisible on CPU tests except as flakiness.

`RecompileGuard` counts backend compiles via `jax.monitoring` duration
events (one `/jax/core/compile/backend_compile_duration` event per
actual XLA compile, cache hits emit none) across a region that should
be steady-state, and `check()` fails when the count exceeds the
allowance.  `CacheProbe` does the same for an explicit list of jitted
callables via their `_cache_size()` — sharper attribution when you know
which functions must stay warm.

JAX is imported lazily: the AST lint half of `tpu6824.analysis` stays
importable (and fast) without it.
"""

from __future__ import annotations

from tpu6824.obs import metrics as _metrics

_compile_events = 0
_listener_registered = False

# Registry mirror of the compile count (module scope per the tpusan
# metric-unregistered rule): once a listener is registered, every
# backend compile also bumps `jitguard.compiles`, which the pulse layer
# turns into a rate series the watchdog's steady-state jit-recompile
# rule fires on.
_M_COMPILES = _metrics.counter("jitguard.compiles")


def _ensure_listener() -> None:
    """Register the (process-global, permanent) compile-event listener.
    jax.monitoring has no unregister that doesn't clobber other
    listeners, so we register once and count forever; guards take
    deltas."""
    global _listener_registered
    if _listener_registered:
        return
    import jax.monitoring

    def _on_duration(event: str, duration: float, **kw) -> None:
        global _compile_events
        if event == "/jax/core/compile/backend_compile_duration":
            _compile_events += 1
            _M_COMPILES.inc()

    jax.monitoring.register_event_duration_secs_listener(_on_duration)
    _listener_registered = True


def compile_count() -> int:
    """Process-lifetime backend-compile count (0 until the first guard
    registers the listener)."""
    return _compile_events


class RecompileGuard:
    """Context manager asserting a region performs at most
    `max_compiles` backend compiles (default 0: steady state).

        fabric.step(30)                  # warm up every variant
        with RecompileGuard() as g:
            fabric.step(100)             # must hit caches only
        g.check()                        # raises RecompileError on miss

    `check()` is implicit at __exit__ when `strict=True` (default); pass
    strict=False to inspect `g.compiles` without raising.
    """

    def __init__(self, max_compiles: int = 0, strict: bool = True):
        self.max_compiles = max_compiles
        self.strict = strict
        self.compiles = 0
        self._t0 = 0

    def __enter__(self) -> "RecompileGuard":
        _ensure_listener()
        self._t0 = _compile_events
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        self.compiles = _compile_events - self._t0
        if self.strict and exc_type is None:
            self.check()
        return False

    def check(self) -> None:
        if self.compiles > self.max_compiles:
            raise RecompileError(
                f"{self.compiles} backend compile(s) in a region budgeted "
                f"for {self.max_compiles} — a shape/static-arg is varying "
                "in steady state (see tpusan jitguard)")


class RecompileError(AssertionError):
    pass


class CacheProbe:
    """Per-function cache-miss attribution: snapshot `_cache_size()` of
    known jitted callables, re-sample later, report which grew."""

    def __init__(self, fns: dict[str, object]):
        self.fns = dict(fns)
        self._base = {k: self._size(f) for k, f in self.fns.items()}

    @staticmethod
    def _size(fn) -> int:
        try:
            return fn._cache_size()
        except AttributeError:
            return -1  # not a pjit function (or API moved): unattributable

    def misses(self) -> dict[str, int]:
        out = {}
        for k, f in self.fns.items():
            d = self._size(f) - self._base[k]
            if d > 0:
                out[k] = d
        return out
