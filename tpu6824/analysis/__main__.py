"""CLI: `python -m tpu6824.analysis [paths...]`.

Exit status 0 iff every finding is suppressed (each suppression carrying
its mandatory justification).  `--json` emits a machine-readable report
(stamped with ANALYZER_VERSION, the CHANGES-artifact form); `--all`
includes suppressed findings in the listing; `--list-rules` documents
the rule set.  No JAX import on this path — the AST pass is pure stdlib.
"""

from __future__ import annotations

import argparse
import json
import sys

from tpu6824.analysis.lint import ANALYZER_VERSION, RULES, lint_paths


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tpu6824.analysis",
        description="tpusan — lock-discipline & determinism lint")
    ap.add_argument("paths", nargs="*", default=["tpu6824"],
                    help="files or directories to lint (default: tpu6824)")
    ap.add_argument("--json", action="store_true",
                    help="emit a JSON report on stdout")
    ap.add_argument("--all", action="store_true",
                    help="also list suppressed findings")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--version", action="store_true")
    args = ap.parse_args(argv)

    if args.version:
        print(ANALYZER_VERSION)
        return 0
    if args.list_rules:
        for rule, desc in sorted(RULES.items()):
            print(f"{rule}:\n    {desc}")
        return 0

    findings = lint_paths(args.paths)
    active = [f for f in findings if not f.suppressed]
    suppressed = [f for f in findings if f.suppressed]

    if args.json:
        print(json.dumps({
            "analyzer": ANALYZER_VERSION,
            "paths": args.paths,
            "findings": [vars(f) for f in findings],
            "active": len(active),
            "suppressed": len(suppressed),
        }, indent=2))
    else:
        shown = findings if args.all else active
        for f in sorted(shown, key=lambda f: (f.path, f.line)):
            tag = " [suppressed]" if f.suppressed else ""
            print(f.render() + tag)
        print(f"{ANALYZER_VERSION}: {len(active)} finding(s), "
              f"{len(suppressed)} suppressed")
    return 1 if active else 0


if __name__ == "__main__":
    sys.exit(main())
