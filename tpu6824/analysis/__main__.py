"""CLI: `python -m tpu6824.analysis [paths...]`.

Runs BOTH analysis passes — the per-file tpusan lint and the
whole-program consan concurrency pass — over the same tree.  Exit
status 0 iff every finding is suppressed (each suppression carrying its
mandatory justification).  `--json` emits a machine-readable report
(stamped with ANALYZER_VERSION/CONSAN_VERSION, the CHANGES-artifact
form) including consan's interprocedural lock-order graph; `--all`
includes suppressed findings in the listing; `--list-rules` documents
the rule set.

`--write-baseline` / `--check-baseline` maintain the committed finding
inventory (`tests/data/tpusan/baseline.json`): the baseline records
EVERY finding, suppressed or not, keyed by (path, rule, line-scrubbed
message) so reformatting doesn't churn it, and the tier-1 ratchet test
fails on any drift in either direction — a new finding must be fixed or
justified, a fixed finding must be harvested out of the baseline.

No JAX import on this path — both passes are pure stdlib AST.
"""

from __future__ import annotations

import argparse
import json
import re
import sys

from tpu6824.analysis.consan import CONSAN_VERSION, analyze_paths
from tpu6824.analysis.lint import ANALYZER_VERSION, RULES, lint_paths

BASELINE_DEFAULT = "tests/data/tpusan/baseline.json"

_LINE_REF = re.compile(r":\d+")


def _fingerprint(f) -> tuple[str, str, str]:
    """Identity of a finding across unrelated edits: path + rule + the
    message with embedded line references scrubbed (messages cite other
    sites by line, and those shift with every edit above them)."""
    return (f.path, f.rule, _LINE_REF.sub("", f.msg))


def _baseline_blob(findings) -> dict:
    rows = sorted({_fingerprint(f) for f in findings})
    return {
        "analyzer": ANALYZER_VERSION,
        "consan": CONSAN_VERSION,
        "findings": [
            {"path": p, "rule": r, "msg": m} for p, r, m in rows],
    }


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tpu6824.analysis",
        description="tpusan — lock-discipline & determinism lint + "
                    "consan whole-program concurrency analysis")
    ap.add_argument("paths", nargs="*", default=["tpu6824"],
                    help="files or directories to lint (default: tpu6824)")
    ap.add_argument("--json", action="store_true",
                    help="emit a JSON report on stdout")
    ap.add_argument("--all", action="store_true",
                    help="also list suppressed findings")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--version", action="store_true")
    ap.add_argument("--check-baseline", nargs="?", const=BASELINE_DEFAULT,
                    metavar="FILE",
                    help="fail on any finding drift vs the committed "
                         f"baseline (default {BASELINE_DEFAULT})")
    ap.add_argument("--write-baseline", nargs="?", const=BASELINE_DEFAULT,
                    metavar="FILE",
                    help="regenerate the baseline inventory")
    args = ap.parse_args(argv)

    if args.version:
        print(f"{ANALYZER_VERSION} {CONSAN_VERSION}")
        return 0
    if args.list_rules:
        for rule, desc in sorted(RULES.items()):
            print(f"{rule}:\n    {desc}")
        return 0

    findings = list(lint_paths(args.paths))
    analysis = analyze_paths(args.paths)
    findings += analysis.findings
    active = [f for f in findings if not f.suppressed]
    suppressed = [f for f in findings if f.suppressed]

    if args.write_baseline:
        blob = _baseline_blob(findings)
        with open(args.write_baseline, "w") as fh:
            json.dump(blob, fh, indent=2, sort_keys=True)
            fh.write("\n")
        print(f"baseline: {len(blob['findings'])} finding(s) -> "
              f"{args.write_baseline}")
        return 0

    if args.check_baseline:
        with open(args.check_baseline) as fh:
            base = json.load(fh)
        want = {(r["path"], r["rule"], r["msg"])
                for r in base.get("findings", ())}
        got = {_fingerprint(f) for f in findings}
        added, gone = sorted(got - want), sorted(want - got)
        for p, r, m in added:
            print(f"NEW (fix or justify): {p}: {r}: {m}")
        for p, r, m in gone:
            print(f"GONE (regen baseline with --write-baseline): "
                  f"{p}: {r}: {m}")
        if added or gone:
            print(f"baseline drift: +{len(added)} -{len(gone)} vs "
                  f"{args.check_baseline}")
            return 1

    if args.json:
        print(json.dumps({
            "analyzer": ANALYZER_VERSION,
            "paths": args.paths,
            "findings": [vars(f) for f in findings],
            "active": len(active),
            "suppressed": len(suppressed),
            "consan": {
                "version": CONSAN_VERSION,
                "files": analysis.nfiles,
                "edges": [
                    {"from": a, "to": b, **meta}
                    for (a, b), meta in sorted(analysis.edges.items())],
                "cycles": analysis.cycles(),
                "named_locks": sorted(analysis.named_locks),
            },
        }, indent=2))
    else:
        shown = findings if args.all else active
        for f in sorted(shown, key=lambda f: (f.path, f.line)):
            tag = " [suppressed]" if f.suppressed else ""
            print(f.render() + tag)
        print(f"{ANALYZER_VERSION}+{CONSAN_VERSION}: {len(active)} "
              f"finding(s), {len(suppressed)} suppressed, "
              f"{len(analysis.edges)} lock-order edge(s), "
              f"{len(analysis.cycles())} cycle(s)")
    return 1 if active else 0


if __name__ == "__main__":
    sys.exit(main())
