"""tpusan — project-specific AST lint for the threaded fabric/service stack.

The Go reference leans on `go vet` + `-race`; this rebuild is ~13k lines
of threaded Python whose correctness rests on conventions the compiler
cannot see: what may run under the fabric lock, which paths must be
schedule-deterministic, how daemon threads are allowed to die, and how
`subscribe_decided` consumers must drain the feed.  Each convention is a
rule here, enforced on every PR (tier-1 `tests/test_analysis.py` runs
this over the whole tree), so the bug classes PRs 1–3 fixed cannot be
silently reintroduced by the ROADMAP's scale-out work.

Suppressions: a finding is silenced by a justification comment on the
flagged line (or the line directly above it):

    # tpusan: ok(<rule>[, <rule>...]) — <why this is safe here>

The reason text is mandatory and the rule name must exist — a malformed
or unused suppression is itself a finding (`bad-suppression`,
`unused-suppression`), so the suppression inventory stays auditable.

Pure stdlib (ast + tokenize): the whole pass runs without importing JAX
or any product module.
"""

from __future__ import annotations

import ast
import os
import re
import tokenize
from dataclasses import dataclass, field

ANALYZER_VERSION = "tpusan-1.0.0"

RULES: dict[str, str] = {
    "lock-blocking-call":
        "blocking call (sleep/socket/RPC/device readback/fsync) inside a "
        "fabric/service lock region — stalls every API caller behind it",
    "lock-nested-loop":
        "nested Python for-loops under a fabric/service lock — the "
        "per-cell-loop-under-the-lock regression class (TUNING round 7: "
        "~160ms/retire, halved clerk throughput); keep the work columnar "
        "or move it outside the lock",
    "nondet-clock":
        "wall clock or process-global RNG in a schedule-deterministic "
        "path — use the seeded random.Random / time.monotonic so nemesis "
        "replay stays byte-identical",
    "daemon-bare-except":
        "broad except swallowing failures inside a daemon-thread run "
        "loop without recording them — route through "
        "tpu6824.utils.crashsink (or re-raise) so thread death is never "
        "silent",
    "daemon-crash-sink":
        "threading.Thread(daemon=True) whose target does not route "
        "exceptions to the crash sink — wrap it in crashsink.guarded() "
        "so stats()['health'] reports the death",
    "feed-columnar":
        "subscribe_decided consumer bypassing the columnar feed contract "
        "— drain via DecidedSub.pop()/DecidedTap, never the private "
        "per-batch queue",
    "tracer-leak":
        "jit-traced function writes to host state (self attribute, "
        "closure container, global) — leaks tracers out of the trace and "
        "poisons host mirrors",
    "metric-unregistered":
        "metric created through the obs.metrics registry inside a "
        "function body — metric objects must be created at module scope "
        "(hot loops only call .inc()/.set()/.observe() on them); per-call "
        "get-or-create re-enters the registry lock on the hot path and "
        "hides the metric inventory (registry.inc(), the sanctioned "
        "dynamic-name path, lives inside obs/)",
    "readback-in-step":
        "device readback (jax.device_get / .block_until_ready) in a "
        "step-path module — the kernelscope contract is ONE summary "
        "readback per dispatch (the retire fold), and every protocol "
        "counter rides it; a new readback in the fused step path adds a "
        "host round-trip per dispatch and breaks the zero-extra-readback "
        "guarantee (the two sanctioned retire-fold sites carry justified "
        "suppressions — that inventory IS the contract)",
    "blocking-in-eventloop":
        "sleep / lock-wait / blocking call inside a frontend event-loop "
        "callback (`_on_*` / `*_cb` in the event-loop scope) — the "
        "callback runs ON the epoll loop (or the driver's notify sweep), "
        "so one blocked callback stalls EVERY connection behind it; "
        "callbacks may only decode, enqueue (deque.append), and wake "
        "(Event.set) — park the work on the engine thread instead",
    "durable-write-discipline":
        "open(..., 'w'/'wb') + os.rename/os.replace persistence pattern "
        "outside utils/durafs.py — the bare write-then-rename skips the "
        "tmp fsync (a crash after the rename can publish a file whose "
        "data never hit the platter) and the dir fsync (the rename "
        "itself can be lost), and it bypasses the durafault injection "
        "seam; route the write through durafs.atomic_write()",
    "unbounded-obs-buffer":
        "unbounded list/deque accumulation in tpu6824/obs/ — telemetry "
        "buffers live for the process lifetime and are scraped whole by "
        "pollers, so growth without a cap is a slow leak that lands "
        "exactly when observability matters most (long soaks); give "
        "every ring a cap with counted drops (deque(maxlen=...)) like "
        "the flight recorder does",
    "python-decode-in-native-path":
        "per-op wire decode (struct.unpack / pickle.loads / "
        "int.from_bytes in a loop) inside a frontend event-loop "
        "callback — frame decode belongs to the NATIVE layer (ISSUE "
        "11: the C++ loop parses fe_batch straight into columnar "
        "buffers); a Python per-op decode loop on the callback path "
        "re-creates the GIL-bound ingest wall the native path removed",
    "unbounded-retry":
        "retry loop (while True catching RPCError and continuing) in "
        "rpc/services scope with no visible bound — no deadline, retry "
        "budget, backoff, timeout, or sleep/wait pacing in the loop "
        "body.  An unbounded retry loop is the raw material of a retry "
        "storm: under overload every such clerk amplifies the load "
        "that is failing it (ISSUE 12's retry-budget Backoff and "
        "deadline propagation exist to bound exactly this); pace the "
        "loop with services.common.Backoff or bound it by deadline",
    "unbounded-host-state":
        "an RSM apply path (`_apply*` in services scope) grows a "
        "self-attribute dict/list that NOTHING in the class ever "
        "trims, GCs, or snapshot-replaces — every decided op then "
        "grows host memory forever, exactly the class of leak the "
        "horizon compaction machinery (ISSUE 14) exists to bound; "
        "give the store a retirement path (a replicated compact "
        "entry, a del/pop on a resolution event, or a snapshot "
        "install that rebinds it) or suppress with the justification "
        "for why THIS store is the service's actual data",
    "blocking-commit-wait":
        "waiting on a cross-group RPC or future (txn_status / "
        "transfer_state / txn_op / .wait / .result) while holding the "
        "server mutex or inside an _apply* function in services scope "
        "— the classic 2PC deadlock shape: group A's apply blocks on "
        "group B, whose apply blocks on A, and both RSMs stop draining "
        "their logs forever.  Consult coordinators from the ticker "
        "(txnkv.resolve_pass), never under mu or in apply",
    "wallclock-duration":
        "time.time() delta used as a duration in rpc/services/core "
        "scope — the wall clock jumps under NTP slew and the nemesis "
        "clock-pause fault, corrupting timeouts and latency accounting "
        "(opscope's whole stage waterfall is monotonic-ns by "
        "invariant); compute durations from time.monotonic()/"
        "monotonic_ns(), keep time.time() for human-facing timestamps "
        "only",
    "host-walk-in-decided-path":
        "per-op host dict walk keyed by the op's key (store[op.key] "
        "get/set, store.get(op.key)) inside an `_apply*` / decide-drain "
        "function of a decided-path service module — the decided path "
        "applies as ONE columnar device step (ISSUE 16 devapply: intern "
        "probe + int columns, no per-op dict walk, no per-op str "
        "concat); non-hot ops that legitimately stay host-side "
        "(reconfig/compact/txn, the host fallback engine) carry "
        "justified suppressions — that inventory IS the hot-path "
        "contract",
    "host-sync-in-sharded-step":
        "host synchronization (np.asarray / jax.device_get / "
        ".block_until_ready) inside a sharded-step or per-shard "
        "dispatch/drain function in mesh scope — the sharded fabric "
        "step runs ONE fused program across every mesh shard, and a "
        "host sync inside it serializes the whole mesh behind a single "
        "device round-trip (ISSUE 17 meshfab: decide feeds drain "
        "per-shard with no cross-device host sync); read back via "
        "DevicePlane.fetch_host on the snapshot path, off the step",
    "frontend-local-dedup":
        "dup/at-most-once state (attribute names mentioning dup/dedup/"
        "seen/last_reply/replied) grown on a *Frontend* class in "
        "services scope — the frontend tier is horizontally replaceable "
        "(fleetfe, ISSUE 18): a clerk's retry after a frontend death "
        "lands on a DIFFERENT frontend, so an at-most-once decision "
        "made from frontend-local memory answers from state the rest "
        "of the fleet cannot see (stale dup hit, or a double-apply the "
        "local table never heard about); dedupe through the replicated "
        "dup table the RSM applies, and keep frontends stateless",
    "blocking-io-in-telemetry-path":
        "blocking filesystem IO (open/os.write/fsync/msync/flush) "
        "reachable from a telemetry clock body in tpu6824/obs/ — a "
        "pulse observer/sampler tick, an opscope fold, or a drain pass "
        "— outside the sanctioned blackbox cadence seam "
        "(Recorder.sync/_sync_loop).  Telemetry paths run on sampling "
        "and drain clocks shared with the serving path; one slow disk "
        "turns the observability plane into the outage (ISSUE 20's "
        "whole design: producers do GIL-atomic memory stores, the ONE "
        "sync seam does the msync on its own cadence).  Move the IO "
        "into the blackbox seam, or suppress with the measured cost "
        "and why the clock tolerates it",
    "bad-suppression":
        "malformed tpusan suppression: needs ok(<known-rule>) and a "
        "non-empty justification after a dash",
    "unused-suppression":
        "tpusan suppression that matches no finding — stale after a "
        "refactor or rule change; delete it or fix the rule name",
}

# Whole-program rules owned by the consan pass (analysis/consan.py) —
# registered here so the suppression loader accepts `ok(...)` comments
# naming them, but NOT run by the per-file visitor: consan needs the
# whole call graph at once.  The per-file unused-suppression check
# defers suppressions naming only these rules to consan (which alone
# can tell whether they match).
WHOLE_PROGRAM_RULES: dict[str, str] = {
    "lock-order-cycle":
        "cycle in the static lock-order graph — two code paths (possibly "
        "crossing function/module boundaries) acquire the same locks in "
        "opposite orders, which deadlocks the moment two threads "
        "interleave them; fix the acquisition order or drop one side to "
        "a try-acquire",
    "lock-manifest-order":
        "static lock acquisition edge against the canonical order "
        "declared in tpu6824.utils.locks.MANIFEST (outermost first) — "
        "either the code path is wrong or the manifest is; change "
        "whichever is lying, never suppress silently",
    "lock-manifest-missing":
        "named lock (utils.locks.new_lock/new_rlock) absent from the "
        "canonical MANIFEST in tpu6824/utils/locks.py — every named hot "
        "lock declares its rank so static consan and runtime lockwatch "
        "can validate the same hierarchy",
    "unlocked-shared-state":
        "self attribute written under the class lock in one method but "
        "touched lock-free from a method a different thread class "
        "reaches — the devapply mirror-cadence race shape (PR 15); "
        "either take the lock at the bare site or justify why it is "
        "safe (immutable snapshot swap, single-writer field, monotonic "
        "counter read)",
    "lock-blocking-reachable":
        "blocking call (sleep/socket/RPC/device readback/.wait) "
        "reachable through the call graph while a named/server lock is "
        "held — the interprocedural half of lock-blocking-call: the "
        "lexical rule sees `with mu: sleep()`, this sees `with mu: "
        "helper()` where the sleep hides two calls down, stalling every "
        "thread behind the lock",
}
RULES.update(WHOLE_PROGRAM_RULES)

# ---------------------------------------------------------------- scopes

_LOCK_SCOPE = (
    "core/fabric.py", "core/fabric_service.py", "core/hostpeer.py",
    "core/intern.py", "services/",
)
_DET_SCOPE = ("harness/nemesis.py", "harness/linearize.py")
# The fused step path: modules whose dispatch loop the zero-extra-readback
# contract covers (kernel rounds, the fabric clock, the sharded mesh).
_STEP_SCOPE = ("core/kernel.py", "core/pallas_kernel.py",
               "core/fabric.py", "parallel/mesh.py", "core/fabdev.py")
# Calls that force a device→host round-trip.
_READBACK_TAILS = {"device_get", "block_until_ready"}
# Mesh-fabric scope (host-sync-in-sharded-step): the sharded execution
# path and the fabric's device plane.  Functions named `sharded_*` or
# whose name mentions dispatch/drain run once per fused step across
# every shard — a host sync there stalls the whole mesh.
# DevicePlane.fetch_host is the sanctioned shard-local readback
# (snapshot path, not the step path) and does not match the filter.
_MESHSTEP_SCOPE = ("parallel/", "core/fabdev.py")
_MESHSTEP_SYNC_DOTTED = {"np.asarray", "numpy.asarray", "jax.device_get"}
_FEED_HOME = "core/fabric.py"  # the only module allowed to touch sub._q
_MET_HOME = "obs/"  # the registry itself may get-or-create anywhere
# The one module allowed to write-then-rename raw: the durable-write seam
# itself (which is also where the disk-fault injector lives).
_DURAFS_HOME = "utils/durafs.py"
_RENAME_CALLS = {"os.rename", "os.replace"}
# Event-loop callback scope (blocking-in-eventloop): the clerk frontend's
# inline callbacks and the native server's epoll-thread hooks.  Callback
# convention: `_on_*` / `*_cb` function names inside these modules.
_EVENTLOOP_SCOPE = ("services/frontend.py", "rpc/native_server.py")
# Observability-buffer scope (unbounded-obs-buffer): every obs/ module —
# pulse rings, flight recorder, watchdog incidents all hold process-
# lifetime state that pollers serialize whole.
_OBS_BUF_SCOPE = ("obs/",)
# Native-path scope (python-decode-in-native-path): the clerk frontend
# and the native server wrapper, whose inline callbacks must never decode
# per-op in Python now that the fe wire decodes in C++ (rpc/wire.py is
# the schema's Python side and is exempt — it IS the fallback decoder,
# running outside the event loop).
_NATIVE_PATH_SCOPE = ("services/frontend.py", "rpc/native_server.py")
_DECODE_DOTTED = {"struct.unpack", "struct.unpack_from", "pickle.loads",
                  "pickle.load"}
_DECODE_TAILS = {"unpack", "unpack_from", "from_bytes"}
# Commit-wait scope (blocking-commit-wait): the service layer, where
# RSM apply paths and server mutexes live.
_COMMIT_SCOPE = ("services/",)
# Frontend-dedup scope (frontend-local-dedup): the service layer again,
# but keyed by CLASS name — the rule polices classes named *Frontend*
# (the horizontally-replaceable serving tier), not the RSM servers,
# whose replicated `self.dup` tables are exactly where dedup belongs.
_FE_DEDUP_SCOPE = ("services/",)
_FE_DEDUP_ATTR_RE = re.compile(
    r"dup|dedup|seen|last_?reply|replied", re.IGNORECASE)
# Decided-path scope (host-walk-in-decided-path): the RSM services whose
# apply/drain loops the devapply columnar contract covers (ISSUE 16).
# Key-keyed store walks there belong on the device; cid-keyed waiter/dup
# probes are O(1) bookkeeping and are NOT flagged (the rule keys on the
# op's `.key`).
_DECIDED_SCOPE = ("services/kvpaxos.py", "services/shardkv.py",
                  "services/txnkv.py")
# The dict verbs that constitute a store walk when their key argument is
# the op's key.
_DECIDED_WALK_VERBS = {"get", "setdefault"}
# Retry-loop scope (unbounded-retry): anywhere clerks/transports retry
# RPCs.  A loop counts as BOUNDED when its body references any of these
# identifier substrings (deadlines, budgets, backoffs, timeouts) or
# paces itself with a sleep/wait call.
_RETRY_SCOPE = ("rpc/", "services/")
_RETRY_BOUND_SUBSTR = ("deadline", "budget", "backoff", "timeout")
_RETRY_PACE_TAILS = {"sleep", "wait"}
# Wallclock-duration scope (wallclock-duration): the layers whose
# timeouts, retries, and latency accounting feed decisions — the rpc
# transports, the service RSMs, and the fabric core.  Harness modules
# already have the stricter nondet-clock rule.
_WALLDUR_SCOPE = ("rpc/", "services/", "core/")
_WALL_CALLS = ("time.time", "time.time_ns")
# Telemetry-IO scope (blocking-io-in-telemetry-path): every obs/ module.
# ENTRY functions — the bodies that run on a telemetry clock — are
# `_on_*` callbacks plus any function whose name mentions a sampling/
# fold/drain verb; the SEAM names are blackbox's sanctioned cadence
# sync, excluded as entries and never traversed into.  Reachability is
# same-file (bare-name and self-method calls), matching the other
# per-file scans.
_TELEM_SCOPE = ("obs/",)
_TELEM_ENTRY_SUBSTR = ("sample", "fold", "drain", "tick", "observer")
_TELEM_SEAM_NAMES = {"sync", "_sync_loop"}
_TELEM_IO_DOTTED = {"open", "io.open", "os.open", "os.write", "os.fsync",
                    "os.fdatasync", "os.sync"}
_TELEM_IO_TAILS = {"flush", "fsync", "msync", "fdatasync"}

# Receivers that denote the tpuscope metrics registry, and the
# get-or-create constructors the metric-unregistered rule polices.
_MET_RECEIVERS = {"metrics", "_metrics", "obs_metrics", "REGISTRY",
                  "registry"}
_MET_CREATORS = {"counter", "gauge", "histogram"}

# Attribute names that denote "the lock" in fabric/feed/service code.
_LOCK_ATTRS = {"_lock", "mu", "_fs_lock"}

# Blocking calls by full dotted name...
_BLOCKING_DOTTED = {
    "time.sleep", "jax.device_get", "os.fsync", "socket.create_connection",
    "subprocess.run", "subprocess.Popen", "subprocess.check_output",
    "subprocess.check_call", "select.select",
}
# ... and by attribute tail on any receiver (sockets, RPC stubs, device
# arrays).  `.sleep` also catches Backoff.sleep; `.call` catches the
# pooled transport / FlakyNet RPC legs.
_BLOCKING_TAILS = {
    "recv", "recv_into", "sendall", "accept", "connect",
    "block_until_ready", "device_get", "fsync", "sleep", "call",
}

# Module-level `random.X` calls that consume the process-global RNG.
_GLOBAL_RNG = {
    "random", "randint", "randrange", "choice", "choices", "uniform",
    "shuffle", "sample", "getrandbits", "gauss", "betavariate", "expovariate",
}
_WALL_CLOCK = {"time.time", "time.time_ns", "datetime.now", "datetime.utcnow"}

# Additional blocking tails for the event-loop rule: a callback must not
# even WAIT on a lock/event (lock-blocking-call tolerates `with mu` and
# polices only what runs inside; a loop callback may not pause at all).
_EVENTLOOP_BLOCK_TAILS = _BLOCKING_TAILS | {"acquire", "wait", "join"}

# Cross-group waits the blocking-commit-wait rule polices (ISSUE 13):
# consulting another group's state or parking on a future while holding
# the server mutex (lock region / *_locked convention) or inside an
# _apply* function is the 2PC deadlock shape.  Scope: services/.
_COMMIT_WAIT_TAILS = {"wait", "result", "txn_status", "transfer_state",
                      "txn_op"}

_SUPPRESS_RE = re.compile(
    r"tpusan:\s*ok\(\s*([\w*,\s-]+?)\s*\)\s*(?:[—–:]|-{1,2})?\s*(.*)")


@dataclass
class Finding:
    path: str
    line: int
    rule: str
    msg: str
    suppressed: bool = False

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.rule}: {self.msg}"


@dataclass
class Suppression:
    line: int          # source line the comment sits on
    rules: set[str]
    reason: str
    used: bool = field(default=False)


def _dotted(node: ast.AST) -> str | None:
    """'a.b.c' for Name/Attribute chains, else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _in_scope(relpath: str, scope: tuple[str, ...]) -> bool:
    # Scope entries are package-relative path suffixes like
    # "core/fabric.py" or directory infixes like "services/"; `relpath`
    # may be absolute — matching is suffix/infix based.
    p = "/" + relpath.lstrip("/")
    for s in scope:
        if s.endswith("/"):
            if f"/{s}" in p:
                return True
        elif p.endswith("/" + s) or relpath == s:
            return True
    return False


# ------------------------------------------------------------ suppressions


def _collect_suppressions(source: str, path: str,
                          findings: list[Finding]) -> dict[int, Suppression]:
    sups: dict[int, Suppression] = {}
    try:
        tokens = tokenize.generate_tokens(iter(source.splitlines(True)).__next__)
        comments = [(t.start[0], t.string) for t in tokens
                    if t.type == tokenize.COMMENT]
    except tokenize.TokenError:
        comments = []
    for line, text in comments:
        if "tpusan:" not in text:
            continue  # prose MENTIONING tpusan is not a suppression
        m = _SUPPRESS_RE.search(text)
        if not m:
            findings.append(Finding(
                path, line, "bad-suppression",
                "tpusan comment does not parse as ok(<rule>) — <reason>"))
            continue
        rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
        reason = m.group(2).strip()
        bad = [r for r in rules if r != "*" and r not in RULES]
        if bad:
            findings.append(Finding(
                path, line, "bad-suppression",
                f"unknown rule(s) in suppression: {', '.join(sorted(bad))}"))
            continue
        if not reason:
            findings.append(Finding(
                path, line, "bad-suppression",
                "suppression carries no justification — say WHY it is safe"))
            continue
        sups[line] = Suppression(line, rules, reason)
    return sups


# ------------------------------------------------------------ the visitor


class _FileLint(ast.NodeVisitor):
    def __init__(self, path: str, relpath: str, tree: ast.Module):
        self.path = path
        self.rel = relpath
        self.tree = tree
        self.findings: list[Finding] = []
        self.lock_scope = _in_scope(relpath, _LOCK_SCOPE)
        self.det_scope = _in_scope(relpath, _DET_SCOPE)
        self.step_scope = _in_scope(relpath, _STEP_SCOPE)
        self.feed_home = _in_scope(relpath, (_FEED_HOME,))
        self.met_home = _in_scope(relpath, (_MET_HOME,))
        self.durafs_home = _in_scope(relpath, (_DURAFS_HOME,))
        self.eventloop_scope = _in_scope(relpath, _EVENTLOOP_SCOPE)
        self.obs_buf_scope = _in_scope(relpath, _OBS_BUF_SCOPE)
        self.native_path_scope = _in_scope(relpath, _NATIVE_PATH_SCOPE)
        self.retry_scope = _in_scope(relpath, _RETRY_SCOPE)
        self.commit_scope = _in_scope(relpath, _COMMIT_SCOPE)
        self.walldur_scope = _in_scope(relpath, _WALLDUR_SCOPE)
        self.decided_scope = _in_scope(relpath, _DECIDED_SCOPE)
        self.meshstep_scope = _in_scope(relpath, _MESHSTEP_SCOPE)
        self.telem_scope = _in_scope(relpath, _TELEM_SCOPE)
        self._lock_depth = 0       # with <lock> nesting
        self._loop_depth_in_lock = 0
        self._daemon_targets = self._resolve_daemon_targets()
        self._jit_defs = self._resolve_jit_defs()
        self._scan_persistence()
        self._scan_apply_growth()
        self._scan_frontend_dedup()
        self._scan_decided_walks()
        self._scan_eventloop_callbacks()
        self._scan_native_decode()
        self._scan_meshstep_sync()
        self._scan_obs_buffers()
        self._scan_retry_loops()
        self._scan_wallclock_durations()
        self._scan_telemetry_io()
        self._fn_stack: list[ast.AST] = []
        self._calls_subscribe = False
        self._refs_columnar_consumer = False

    # ------------------------------------------------ module-level scans

    def _all_defs(self) -> dict[str, list[ast.AST]]:
        defs: dict[str, list[ast.AST]] = {}
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                defs.setdefault(node.name, []).append(node)
        return defs

    def _resolve_daemon_targets(self) -> dict[int, ast.AST]:
        """Map Thread(target=..., daemon=True) call sites to the resolved
        target FunctionDef (None if unresolvable/unguarded) — plus record
        the daemon-crash-sink findings right here."""
        defs = self._all_defs()
        targets: dict[int, ast.AST] = {}
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call):
                continue
            fname = _dotted(node.func)
            if fname not in ("threading.Thread", "Thread"):
                continue
            if not any(kw.arg == "daemon" and
                       isinstance(kw.value, ast.Constant) and
                       kw.value.value is True for kw in node.keywords):
                continue
            target = next((kw.value for kw in node.keywords
                           if kw.arg == "target"), None)
            if target is None:
                continue
            # target=crashsink.guarded(...) / guarded(...): satisfied —
            # but the wrapped function is still a daemon run loop, so
            # resolve it and lint its except handlers.
            if isinstance(target, ast.Call):
                tn = _dotted(target.func) or ""
                if not tn.endswith("guarded"):
                    self._flag(node, "daemon-crash-sink",
                               "daemon thread target is an unrecognized "
                               "call expression — wrap it in "
                               "crashsink.guarded()")
                    continue
                inner = target.args[0] if target.args else None
                iname = None
                if isinstance(inner, ast.Attribute):
                    iname = inner.attr
                elif isinstance(inner, ast.Name):
                    iname = inner.id
                for fn in defs.get(iname or "", []):
                    targets[id(fn)] = fn
                continue
            name = None
            if isinstance(target, ast.Attribute):
                name = target.attr
            elif isinstance(target, ast.Name):
                name = target.id
            cand = defs.get(name or "", [])
            if not cand:
                self._flag(node, "daemon-crash-sink",
                           f"cannot resolve daemon target {name!r} in this "
                           "module — wrap it in crashsink.guarded()")
                continue
            fn = cand[0]
            if self._mentions_crashsink(fn):
                targets[id(fn)] = fn
                continue
            self._flag(node, "daemon-crash-sink",
                       f"daemon target {name}() never touches the crash "
                       "sink — wrap the spawn in crashsink.guarded() or "
                       "record() from the loop")
            targets[id(fn)] = fn  # still lint its except handlers
        return targets

    @staticmethod
    def _mentions_crashsink(fn: ast.AST) -> bool:
        for n in ast.walk(fn):
            if isinstance(n, ast.Name) and n.id == "crashsink":
                return True
            if isinstance(n, ast.Attribute) and n.attr in (
                    "guarded", "record") and \
                    isinstance(n.value, ast.Name) and \
                    n.value.id == "crashsink":
                return True
        return False

    def _scan_persistence(self) -> None:
        """durable-write-discipline: a function that opens a file for
        writing AND renames/replaces is (re)implementing the atomic-
        persist pattern by hand — outside utils/durafs.py that skips the
        fsync discipline and the fault-injection seam.  Flagged at each
        write-open (the write is what loses data)."""
        if self.durafs_home:
            return

        def write_mode(call: ast.Call):
            mode = None
            if len(call.args) >= 2 and isinstance(call.args[1], ast.Constant):
                mode = call.args[1].value
            for kw in call.keywords:
                if kw.arg == "mode" and isinstance(kw.value, ast.Constant):
                    mode = kw.value.value
            return isinstance(mode, str) and ("w" in mode or "x" in mode)

        flagged: set[int] = set()  # a nested def is walked twice
        for fn in ast.walk(self.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            opens, renames = [], False
            for n in ast.walk(fn):
                if not isinstance(n, ast.Call):
                    continue
                d = _dotted(n.func)
                if d in ("open", "io.open") and write_mode(n):
                    opens.append(n)
                elif d in _RENAME_CALLS:
                    renames = True
            if renames:
                for n in opens:
                    if id(n) not in flagged:
                        flagged.add(id(n))
                        self._flag(n, "durable-write-discipline",
                                   "write-then-rename persistence outside "
                                   "the durafs seam — use "
                                   "durafs.atomic_write()")

    def _scan_apply_growth(self) -> None:
        """unbounded-host-state: per class in services scope, find
        self-attributes GROWN inside `_apply*` methods (subscript
        assignment, append/add/extend/insert/setdefault) with no trim
        evidence anywhere else in the class — no `del self.X[...]`,
        no pop/popitem/clear/remove/discard/retire_below call, and no
        rebinding `self.X = ...` outside __init__ (a snapshot install
        that replaces the store wholesale counts as the GC path).
        One finding per (class, attr), at the first growth site."""
        if not self.commit_scope:
            return
        grow_verbs = {"append", "add", "extend", "insert", "setdefault"}
        trim_verbs = {"pop", "popitem", "clear", "remove", "discard",
                      "retire_below"}

        def self_attr(node) -> str | None:
            if isinstance(node, ast.Attribute) and \
                    isinstance(node.value, ast.Name) and \
                    node.value.id == "self":
                return node.attr
            return None

        for cls in ast.walk(self.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            grown: dict[str, ast.AST] = {}  # attr -> first growth site
            trimmed: set[str] = set()
            for fn in cls.body:
                if not isinstance(fn, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                    continue
                in_apply = fn.name.startswith("_apply")
                in_init = fn.name == "__init__"
                for n in ast.walk(fn):
                    if isinstance(n, ast.Assign):
                        for t in n.targets:
                            if isinstance(t, ast.Subscript):
                                a = self_attr(t.value)
                                if a and in_apply and a not in grown:
                                    grown[a] = n
                            else:
                                a = self_attr(t)
                                if a and not in_init:
                                    trimmed.add(a)  # rebinding path
                    elif isinstance(n, ast.Delete):
                        for t in n.targets:
                            if isinstance(t, ast.Subscript):
                                a = self_attr(t.value)
                                if a:
                                    trimmed.add(a)
                    elif isinstance(n, ast.Call) and \
                            isinstance(n.func, ast.Attribute):
                        a = self_attr(n.func.value)
                        if a is None:
                            continue
                        if n.func.attr in trim_verbs:
                            trimmed.add(a)
                        elif n.func.attr in grow_verbs and in_apply \
                                and a not in grown:
                            grown[a] = n
            for attr, site in grown.items():
                if attr in trimmed:
                    continue
                self._flag(site, "unbounded-host-state",
                           f"self.{attr} grows in an _apply path of "
                           f"{cls.name} with no trim/GC/snapshot-"
                           "replace path anywhere in the class — "
                           "unbounded host state on the decided path")

    def _scan_frontend_dedup(self) -> None:
        """frontend-local-dedup: inside classes named *Frontend* in
        services scope, flag growth of self-attribute state whose name
        reads as dup/at-most-once bookkeeping (subscript assignment or
        add/setdefault/append on `self.<dup-ish>`).  The RSM servers'
        replicated `self.dup` tables live in classes NOT named
        *Frontend* and stay clean; a frontend caching "already answered
        (cid, cseq)" locally is exactly the state a migrated retry
        cannot see.  One finding per (class, attr), at the first growth
        site."""
        if not _in_scope(self.rel, _FE_DEDUP_SCOPE):
            return
        grow_verbs = {"add", "setdefault", "append", "put"}

        def self_attr(node) -> str | None:
            if isinstance(node, ast.Attribute) and \
                    isinstance(node.value, ast.Name) and \
                    node.value.id == "self":
                return node.attr
            return None

        for cls in ast.walk(self.tree):
            if not isinstance(cls, ast.ClassDef) or \
                    "Frontend" not in cls.name:
                continue
            flagged: set[str] = set()
            for n in ast.walk(cls):
                attr, site = None, None
                if isinstance(n, ast.Assign):
                    for t in n.targets:
                        if isinstance(t, ast.Subscript):
                            a = self_attr(t.value)
                            if a:
                                attr, site = a, n
                elif isinstance(n, ast.Call) and \
                        isinstance(n.func, ast.Attribute) and \
                        n.func.attr in grow_verbs:
                    a = self_attr(n.func.value)
                    if a:
                        attr, site = a, n
                if attr is None or attr in flagged:
                    continue
                if not _FE_DEDUP_ATTR_RE.search(attr):
                    continue
                flagged.add(attr)
                self._flag(site, "frontend-local-dedup",
                           f"self.{attr} grows dup/at-most-once state "
                           f"inside frontend class {cls.name} — a "
                           "migrated retry lands on a frontend that "
                           "never saw this table; dedupe through the "
                           "replicated dup table instead")

    def _scan_decided_walks(self) -> None:
        """host-walk-in-decided-path: inside `_apply*` / `*drain*`
        functions of the decided-path services, flag per-op host store
        walks keyed by the op's key — subscript get/set on a self-attr
        dict (or a local alias of one: `kv = self.kv`), `.get`/
        `.setdefault` calls on them, and calls through bound-verb
        aliases (`kv_get = kv.get`).  A walk counts only when its key
        expression derives from the op's key (`v.key` / `op.key` / a
        `key` local), so cid-keyed waiter/dup bookkeeping stays clean.
        One finding per (function, attr), at the first walk site."""
        if not self.decided_scope:
            return

        def self_attr(node) -> str | None:
            if isinstance(node, ast.Attribute) and \
                    isinstance(node.value, ast.Name) and \
                    node.value.id == "self":
                return node.attr
            return None

        def keyish(node) -> bool:
            for n in ast.walk(node):
                if isinstance(n, ast.Attribute) and n.attr == "key":
                    return True
                if isinstance(n, ast.Name) and n.id == "key":
                    return True
            return False

        for fn in ast.walk(self.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if not (fn.name.startswith("_apply") or "drain" in fn.name):
                continue
            # Pass 1: alias maps.  `kv = self.kv` names the store;
            # `kv_get = kv.get` / `kv_get = self.kv.get` binds a walk
            # verb to it.
            store_alias: dict[str, str] = {}
            verb_alias: dict[str, str] = {}
            for n in ast.walk(fn):
                if not (isinstance(n, ast.Assign) and len(n.targets) == 1
                        and isinstance(n.targets[0], ast.Name)):
                    continue
                name = n.targets[0].id
                attr = self_attr(n.value)
                if attr is not None:
                    store_alias[name] = attr
                    continue
                v = n.value
                if isinstance(v, ast.Attribute) \
                        and v.attr in _DECIDED_WALK_VERBS:
                    base = self_attr(v.value)
                    if base is None and isinstance(v.value, ast.Name):
                        base = store_alias.get(v.value.id)
                    if base is not None:
                        verb_alias[name] = base

            def store_of(node) -> str | None:
                a = self_attr(node)
                if a is not None:
                    return a
                if isinstance(node, ast.Name):
                    return store_alias.get(node.id)
                return None

            first: dict[str, ast.AST] = {}  # attr -> earliest walk site

            def flag(site, attr):
                # ast.walk is breadth-first, not source order: keep the
                # EARLIEST site so the finding (and its suppression)
                # anchors where a reader first meets the walk.
                prev = first.get(attr)
                if prev is None or site.lineno < prev.lineno:
                    first[attr] = site

            # Pass 2: walk sites.
            for n in ast.walk(fn):
                if isinstance(n, ast.Subscript):
                    attr = store_of(n.value)
                    if attr is not None and keyish(n.slice):
                        flag(n, attr)
                elif isinstance(n, ast.Call):
                    f = n.func
                    if isinstance(f, ast.Attribute) \
                            and f.attr in _DECIDED_WALK_VERBS:
                        attr = store_of(f.value)
                        if attr is not None and n.args \
                                and any(keyish(a) for a in n.args):
                            flag(n, attr)
                    elif isinstance(f, ast.Name) and f.id in verb_alias \
                            and n.args and any(keyish(a) for a in n.args):
                        flag(n, verb_alias[f.id])
            for attr, site in sorted(first.items(),
                                     key=lambda kv: kv[1].lineno):
                self._flag(site, "host-walk-in-decided-path",
                           f"self.{attr} walked per op by key in "
                           f"{fn.name} — the decided path applies as "
                           "one columnar device step (devapply); keep "
                           "key-addressed state off the host here or "
                           "justify why this op class stays host-side")

    def _scan_eventloop_callbacks(self) -> None:
        """blocking-in-eventloop: inside an event-loop callback (`_on_*`
        / `*_cb` in the event-loop scope) flag every blocking call —
        sleeps, socket/RPC legs, device readbacks, and any lock/event
        wait (`.acquire`/`.wait`/`.join`, `with <lock>`).  Nested defs
        are excluded (a closure handed elsewhere runs elsewhere)."""
        if not self.eventloop_scope:
            return
        for fn in ast.walk(self.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if not (fn.name.startswith("_on_") or fn.name.endswith("_cb")):
                continue
            skip: set[int] = set()
            for n in ast.walk(fn):
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                        and n is not fn:
                    skip.update(id(m) for m in ast.walk(n))
            for n in ast.walk(fn):
                if id(n) in skip:
                    continue
                if isinstance(n, ast.Call):
                    d = _dotted(n.func)
                    if d is None:
                        continue
                    tail = d.rsplit(".", 1)[-1]
                    if d in _BLOCKING_DOTTED or (
                            "." in d and tail in _EVENTLOOP_BLOCK_TAILS):
                        self._flag(n, "blocking-in-eventloop",
                                   f"{d}() inside event-loop callback "
                                   f"{fn.name}() — decode/enqueue/wake "
                                   "only; hand the work to the engine "
                                   "thread")
                elif isinstance(n, ast.With):
                    if any(self._is_lock_expr(item.context_expr)
                           for item in n.items):
                        self._flag(n, "blocking-in-eventloop",
                                   f"lock wait (`with` on a lock) inside "
                                   f"event-loop callback {fn.name}()")

    def _scan_native_decode(self) -> None:
        """python-decode-in-native-path: inside a frontend event-loop
        callback (`_on_*` / `*_cb`), flag per-op wire-decode calls —
        struct.unpack(_from), pickle.loads, int.from_bytes — that sit
        INSIDE a for/while loop.  One-shot header reads outside a loop
        are tolerated (cheap, bounded); a decode LOOP on the callback
        thread is the regression the native ingest path exists to
        prevent.  Nested defs are excluded, as in the blocking rule."""
        if not self.native_path_scope:
            return
        for fn in ast.walk(self.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if not (fn.name.startswith("_on_") or fn.name.endswith("_cb")):
                continue
            skip: set[int] = set()
            for n in ast.walk(fn):
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                        and n is not fn:
                    skip.update(id(m) for m in ast.walk(n))
            flagged: set[int] = set()  # a call under nested loops: once
            for loop in ast.walk(fn):
                if id(loop) in skip or \
                        not isinstance(loop, (ast.For, ast.While)):
                    continue
                for n in ast.walk(loop):
                    if id(n) in skip or id(n) in flagged or \
                            not isinstance(n, ast.Call):
                        continue
                    d = _dotted(n.func)
                    if d is None:
                        continue
                    tail = d.rsplit(".", 1)[-1]
                    if d in _DECODE_DOTTED or (
                            "." in d and tail in _DECODE_TAILS):
                        flagged.add(id(n))
                        self._flag(n, "python-decode-in-native-path",
                                   f"{d}() in a loop inside event-loop "
                                   f"callback {fn.name}() — per-op frame "
                                   "decode belongs to the native ingest "
                                   "layer (rpcserver.cpp + rpc/wire.py)")

    def _scan_meshstep_sync(self) -> None:
        """host-sync-in-sharded-step: np.asarray / jax.device_get /
        .block_until_ready inside a `sharded_*` or dispatch/drain
        function in mesh scope — the fused sharded step must stay
        async across every shard.  Nested defs are excluded (a closure
        handed to jit runs on the device, not the host)."""
        if not self.meshstep_scope:
            return
        for fn in ast.walk(self.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            name = fn.name
            if not (name.startswith("sharded_")
                    or "dispatch" in name or "drain" in name):
                continue
            skip: set[int] = set()
            for n in ast.walk(fn):
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                        and n is not fn:
                    skip.update(id(m) for m in ast.walk(n))
            for n in ast.walk(fn):
                if id(n) in skip or not isinstance(n, ast.Call):
                    continue
                d = _dotted(n.func)
                if d is None:
                    continue
                tail = d.rsplit(".", 1)[-1]
                if d in _MESHSTEP_SYNC_DOTTED or (
                        "." in d and tail in _READBACK_TAILS):
                    self._flag(n, "host-sync-in-sharded-step",
                               f"{d}() synchronizes with the host inside "
                               f"{name}() — the sharded step must stay "
                               "async across every shard; read back via "
                               "DevicePlane.fetch_host off the step path")

    def _scan_obs_buffers(self) -> None:
        """unbounded-obs-buffer: inside tpu6824/obs/, (a) any deque
        constructed without an explicit maxlen, and (b) any append/
        extend onto a `self.<attr>` that the module initializes as a
        plain list literal — both are accumulation without a cap.
        Fixed-size list attributes (`[0] * N`) and locals are exempt;
        a genuinely-bounded registry (e.g. one observer per watchdog)
        suppresses with a justification."""
        if not self.obs_buf_scope:
            return
        for n in ast.walk(self.tree):
            if isinstance(n, ast.Call):
                d = _dotted(n.func)
                if d in ("deque", "collections.deque") and \
                        not any(kw.arg == "maxlen" for kw in n.keywords):
                    self._flag(n, "unbounded-obs-buffer",
                               "deque without maxlen in an obs module — "
                               "telemetry rings must be bounded with "
                               "counted drops")
        list_attrs: set[str] = set()
        for n in ast.walk(self.tree):
            target = value = None
            if isinstance(n, ast.Assign) and len(n.targets) == 1:
                target, value = n.targets[0], n.value
            elif isinstance(n, ast.AnnAssign) and n.value is not None:
                target, value = n.target, n.value
            if isinstance(target, ast.Attribute) and \
                    isinstance(target.value, ast.Name) and \
                    target.value.id == "self" and \
                    isinstance(value, ast.List):
                list_attrs.add(target.attr)
        for n in ast.walk(self.tree):
            if not (isinstance(n, ast.Call) and
                    isinstance(n.func, ast.Attribute) and
                    n.func.attr in ("append", "extend", "insert")):
                continue
            recv = n.func.value
            if isinstance(recv, ast.Attribute) and \
                    isinstance(recv.value, ast.Name) and \
                    recv.value.id == "self" and recv.attr in list_attrs:
                self._flag(n, "unbounded-obs-buffer",
                           f"self.{recv.attr}.{n.func.attr}() onto an "
                           "uncapped list attribute in an obs module — "
                           "use a deque(maxlen=...) ring with counted "
                           "drops")

    def _scan_retry_loops(self) -> None:
        """unbounded-retry: a `while True:` loop in rpc/services scope
        that catches RPCError without re-raising (the retry shape) and
        whose body shows NO bound — no identifier mentioning a
        deadline/budget/backoff/timeout, no sleep/wait pacing call.
        Nested defs are excluded both ways (their loops are their own
        scope; their bounds don't bound this loop)."""
        if not self.retry_scope:
            return
        for loop in ast.walk(self.tree):
            if not (isinstance(loop, ast.While)
                    and isinstance(loop.test, ast.Constant)
                    and loop.test.value is True):
                continue
            skip: set[int] = set()
            for n in ast.walk(loop):
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    skip.update(id(m) for m in ast.walk(n))
            retries = False
            for n in ast.walk(loop):
                if id(n) in skip or not isinstance(n, ast.ExceptHandler) \
                        or n.type is None:
                    continue
                names = {x.id for x in ast.walk(n.type)
                         if isinstance(x, ast.Name)}
                if "RPCError" in names and not any(
                        isinstance(m, ast.Raise) for m in ast.walk(n)):
                    retries = True
                    break
            if not retries:
                continue
            bound = False
            for n in ast.walk(loop):
                if id(n) in skip:
                    continue
                name = None
                if isinstance(n, ast.Name):
                    name = n.id
                elif isinstance(n, ast.Attribute):
                    name = n.attr
                if name is not None and any(
                        s in name.lower() for s in _RETRY_BOUND_SUBSTR):
                    bound = True
                    break
                if isinstance(n, ast.Call):
                    f = n.func
                    tail = f.attr if isinstance(f, ast.Attribute) else (
                        f.id if isinstance(f, ast.Name) else None)
                    if tail in _RETRY_PACE_TAILS:
                        bound = True
                        break
            if not bound:
                self._flag(loop, "unbounded-retry",
                           "while-True RPC retry loop with no deadline/"
                           "budget/backoff/timeout bound and no pacing "
                           "sleep — a retry storm amplifier; pace it "
                           "with services.common.Backoff or bound it "
                           "by deadline")

    def _scan_wallclock_durations(self) -> None:
        """wallclock-duration: in rpc/services/core scope, a SUBTRACTION
        whose operand is `time.time()`/`time.time_ns()` (directly, or a
        name assigned from one inside the same function) is a duration
        computed from the wall clock — monotonic required (the opscope
        invariant: NTP slew and the clock-pause nemesis make wall-clock
        deltas lie).  Bare `time.time()` stamps (logging, artifact
        metadata) are untouched.  One finding per subtraction site."""
        if not self.walldur_scope:
            return

        def is_wall(n: ast.AST) -> bool:
            return isinstance(n, ast.Call) and _dotted(n.func) in _WALL_CALLS

        flagged: set[int] = set()
        for fn in ast.walk(self.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            # Nested defs are their own scope both ways (the retry-loop
            # rule's discipline): an inner helper's wall-clock stamp
            # must not contaminate the enclosing function's monotonic
            # subtraction — each def is walked on its own visit.
            skip: set[int] = set()
            for n in ast.walk(fn):
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                        and n is not fn:
                    skip.update(id(m) for m in ast.walk(n))
            wall_names: set[str] = set()
            for n in ast.walk(fn):
                if id(n) in skip:
                    continue
                if isinstance(n, ast.Assign) and is_wall(n.value):
                    for t in n.targets:
                        if isinstance(t, ast.Name):
                            wall_names.add(t.id)
                elif isinstance(n, (ast.AnnAssign, ast.AugAssign)) and \
                        n.value is not None and is_wall(n.value) and \
                        isinstance(n.target, ast.Name):
                    wall_names.add(n.target.id)
            for n in ast.walk(fn):
                if id(n) in skip or not (
                        isinstance(n, ast.BinOp)
                        and isinstance(n.op, ast.Sub)) or id(n) in flagged:
                    continue
                for side in (n.left, n.right):
                    if is_wall(side) or (isinstance(side, ast.Name)
                                         and side.id in wall_names):
                        flagged.add(id(n))
                        self._flag(n, "wallclock-duration",
                                   "duration computed from time.time() "
                                   "— wall clock jumps corrupt it; use "
                                   "time.monotonic()/monotonic_ns()")
                        break

    def _scan_telemetry_io(self) -> None:
        """blocking-io-in-telemetry-path: in obs/ scope, walk the
        same-file call graph from every telemetry-clock entry (`_on_*`,
        or a name mentioning sample/fold/drain/tick/observer) and flag
        each blocking-IO call site reached — never traversing INTO the
        sanctioned blackbox seam (`sync`/`_sync_loop`), which is the one
        place telemetry may touch the filesystem.  The finding lands on
        the IO site (where the fix goes) and names the entry + call
        chain that reaches it."""
        if not self.telem_scope:
            return
        defs = self._all_defs()

        def io_desc(n: ast.AST) -> str | None:
            if not isinstance(n, ast.Call):
                return None
            d = _dotted(n.func)
            if d in _TELEM_IO_DOTTED:
                return f"{d}()"
            if isinstance(n.func, ast.Attribute) and \
                    n.func.attr in _TELEM_IO_TAILS:
                return f".{n.func.attr}()"
            return None

        io_sites: dict[str, list] = {}
        callees: dict[str, set[str]] = {}
        for name, fns in defs.items():
            for fn in fns:
                for sub in ast.walk(fn):
                    d = io_desc(sub)
                    if d is not None:
                        io_sites.setdefault(name, []).append((sub, d))
                    if not isinstance(sub, ast.Call):
                        continue
                    f = sub.func
                    cal = None
                    if isinstance(f, ast.Name) and f.id in defs:
                        cal = f.id
                    elif isinstance(f, ast.Attribute) and \
                            isinstance(f.value, ast.Name) and \
                            f.value.id == "self" and f.attr in defs:
                        cal = f.attr
                    if cal is not None:
                        callees.setdefault(name, set()).add(cal)
        flagged: set[int] = set()
        for entry in sorted(defs):
            if entry in _TELEM_SEAM_NAMES:
                continue
            if not (entry.startswith("_on_") or
                    any(s in entry for s in _TELEM_ENTRY_SUBSTR)):
                continue
            seen = {entry}
            queue = [(entry, (entry,))]
            while queue:
                name, chain = queue.pop(0)
                for node, desc in io_sites.get(name, ()):
                    if id(node) in flagged:
                        continue
                    flagged.add(id(node))
                    via = "" if len(chain) == 1 else \
                        " via " + "->".join(chain[1:])
                    self._flag(node, "blocking-io-in-telemetry-path",
                               f"{desc} reachable from telemetry entry "
                               f"{entry}(){via} — blocking IO on a "
                               "sampling/drain clock; only the blackbox "
                               "sync seam may touch the filesystem")
                for cal in sorted(callees.get(name, ())):
                    if cal not in seen and cal not in _TELEM_SEAM_NAMES:
                        seen.add(cal)
                        queue.append((cal, chain + (cal,)))

    def _resolve_jit_defs(self) -> set[int]:
        """FunctionDefs that are jit-compiled: decorated with jax.jit /
        (functools.)partial(jax.jit, ...), or passed by name to
        jax.jit(...) / (jax.)lax.scan(...) anywhere in the module."""
        defs = self._all_defs()
        jit: set[int] = set()

        def is_jit_expr(e: ast.AST) -> bool:
            d = _dotted(e)
            if d in ("jax.jit", "jit", "pl.pallas_call"):
                return True
            if isinstance(e, ast.Call):
                dc = _dotted(e.func)
                if dc in ("functools.partial", "partial") and e.args:
                    return is_jit_expr(e.args[0])
                return is_jit_expr(e.func)
            return False

        for name, fns in defs.items():
            for fn in fns:
                if any(is_jit_expr(d) for d in fn.decorator_list):
                    jit.add(id(fn))
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            d = _dotted(node.func)
            if d in ("jax.jit", "jit"):
                arg = node.args[0]
            elif d in ("jax.lax.scan", "lax.scan"):
                arg = node.args[0]
            else:
                continue
            if isinstance(arg, ast.Name):
                for fn in defs.get(arg.id, []):
                    jit.add(id(fn))
        return jit

    # ------------------------------------------------------------ helpers

    def _flag(self, node: ast.AST, rule: str, msg: str) -> None:
        self.findings.append(
            Finding(self.path, getattr(node, "lineno", 0), rule, msg))

    @staticmethod
    def _is_lock_expr(e: ast.AST) -> bool:
        return isinstance(e, ast.Attribute) and e.attr in _LOCK_ATTRS

    # ------------------------------------------------------------ visits

    def visit_With(self, node: ast.With) -> None:
        is_lock = self.lock_scope and any(
            self._is_lock_expr(item.context_expr) for item in node.items)
        if is_lock:
            self._lock_depth += 1
            saved_loops = self._loop_depth_in_lock
            self._loop_depth_in_lock = 0
            self.generic_visit(node)
            self._loop_depth_in_lock = saved_loops
            self._lock_depth -= 1
        else:
            self.generic_visit(node)

    def visit_For(self, node: ast.For) -> None:
        if self._lock_depth > 0:
            self._loop_depth_in_lock += 1
            if self._loop_depth_in_lock >= 2:
                self._flag(node, "lock-nested-loop",
                           "for-loop nested inside another loop under a "
                           "lock region")
            self.generic_visit(node)
            self._loop_depth_in_lock -= 1
        else:
            self.generic_visit(node)

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        # A nested def's body does not execute under the enclosing lock —
        # but a `*_locked` helper runs under it BY CONVENTION (that is
        # what the suffix promises its callers), so its whole body is a
        # lock region.
        saved = (self._lock_depth, self._loop_depth_in_lock)
        self._lock_depth = (1 if self.lock_scope and
                            node.name.endswith("_locked") else 0)
        self._loop_depth_in_lock = 0
        self._fn_stack.append(node)
        if id(node) in self._jit_defs:
            self._lint_jit_body(node)
        self.generic_visit(node)
        self._fn_stack.pop()
        self._lock_depth, self._loop_depth_in_lock = saved

    visit_AsyncFunctionDef = visit_FunctionDef

    def _in_apply_fn(self) -> bool:
        return any(getattr(f, "name", "").startswith("_apply")
                   for f in self._fn_stack)

    def visit_Call(self, node: ast.Call) -> None:
        d = _dotted(node.func)
        if self._lock_depth > 0 and d is not None:
            tail = d.rsplit(".", 1)[-1]
            if d in _BLOCKING_DOTTED or (
                    "." in d and tail in _BLOCKING_TAILS):
                self._flag(node, "lock-blocking-call",
                           f"call to {d}() under a lock region")
        if self.commit_scope and d is not None and "." in d:
            tail = d.rsplit(".", 1)[-1]
            if tail in _COMMIT_WAIT_TAILS and (
                    self._lock_depth > 0 or self._in_apply_fn()):
                self._flag(node, "blocking-commit-wait",
                           f"{d}() — cross-group wait while holding the "
                           "server mutex / inside an _apply path (the "
                           "2PC deadlock shape); consult coordinators "
                           "from the ticker instead")
        if self.step_scope and d is not None:
            tail = d.rsplit(".", 1)[-1]
            if tail in _READBACK_TAILS:
                self._flag(node, "readback-in-step",
                           f"{d}() forces a device→host round-trip in a "
                           "step-path module — piggyback on the once-per-"
                           "dispatch summary readback instead")
        if self.det_scope and d is not None:
            if d in _WALL_CLOCK:
                self._flag(node, "nondet-clock",
                           f"{d}() in a schedule-deterministic path — use "
                           "time.monotonic()/the schedule clock")
            elif d.startswith("random.") and \
                    d.split(".", 1)[1] in _GLOBAL_RNG:
                self._flag(node, "nondet-clock",
                           f"{d}() consumes the process-global RNG — use "
                           "the seeded random.Random instance")
        if (d is not None and "." in d and not self.met_home
                and self._fn_stack):
            recv, tail = d.rsplit(".", 1)
            if tail in _MET_CREATORS and \
                    recv.rsplit(".", 1)[-1] in _MET_RECEIVERS:
                self._flag(node, "metric-unregistered",
                           f"{d}() inside a function body — create the "
                           "metric at module scope and call "
                           ".inc()/.set()/.observe() here")
        if d is not None and d.endswith("subscribe_decided"):
            # A delegation wrapper (a method itself NAMED subscribe_decided
            # forwarding to the fabric) is not a consumer.
            encl = self._fn_stack[-1] if self._fn_stack else None
            if getattr(encl, "name", None) != "subscribe_decided":
                self._calls_subscribe = True
        self.generic_visit(node)

    def visit_Attribute(self, node: ast.Attribute) -> None:
        if node.attr == "_q" and not self.feed_home:
            self._flag(node, "feed-columnar",
                       "direct access to a DecidedSub's private queue — "
                       "drain via .pop() / DecidedTap")
        # Evidence of sanctioned columnar consumption.  Bare `.pop` is
        # deliberately NOT evidence: every RSM module pops dicts, which
        # would trivially satisfy the rule in exactly the modules it
        # polices.  A consumer using raw DecidedSub.pop() without the
        # tap suppresses with a justification instead.
        if node.attr in ("DecidedTap", "pop_ready"):
            self._refs_columnar_consumer = True
        self.generic_visit(node)

    def visit_Name(self, node: ast.Name) -> None:
        if node.id == "DecidedTap":
            self._refs_columnar_consumer = True
        self.generic_visit(node)

    def visit_ExceptHandler(self, node: ast.ExceptHandler) -> None:
        fn = self._fn_stack[-1] if self._fn_stack else None
        if fn is not None and id(fn) in self._daemon_targets:
            broad = node.type is None or (
                isinstance(node.type, ast.Name) and
                node.type.id in ("Exception", "BaseException"))
            if broad and not self._handler_records(node):
                self._flag(node, "daemon-bare-except",
                           "broad except in a daemon run loop neither "
                           "records the failure nor re-raises")
        self.generic_visit(node)

    @staticmethod
    def _handler_records(node: ast.ExceptHandler) -> bool:
        # `except Exception as e:` whose body actually USES e (stashes it
        # in a record, replies with it, ...) counts as recording.
        if node.name:
            for n in ast.walk(node):
                if isinstance(n, ast.Name) and n.id == node.name:
                    return True
        for n in ast.walk(node):
            if isinstance(n, ast.Raise):
                return True
            if isinstance(n, ast.Call):
                d = _dotted(n.func) or ""
                tail = d.rsplit(".", 1)[-1]
                if tail in ("record", "print_exc", "dprintf", "exception",
                            "error", "warning", "log", "bump"):
                    return True
            if isinstance(n, ast.Name) and n.id == "crashsink":
                return True
        return False

    # ------------------------------------------------------------ jit body

    def _lint_jit_body(self, fn: ast.AST) -> None:
        local: set[str] = {a.arg for a in fn.args.args}
        local.update(a.arg for a in fn.args.kwonlyargs)
        if fn.args.vararg:
            local.add(fn.args.vararg.arg)
        if fn.args.kwarg:
            local.add(fn.args.kwarg.arg)
        inner_defs: set[int] = set()
        for n in ast.walk(fn):
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and n is not fn:
                inner_defs.add(id(n))
                continue
            if isinstance(n, ast.Assign):
                for t in n.targets:
                    for nn in ast.walk(t):
                        if isinstance(nn, ast.Name):
                            local.add(nn.id)
            elif isinstance(n, (ast.AugAssign, ast.AnnAssign)):
                if isinstance(n.target, ast.Name):
                    local.add(n.target.id)
            elif isinstance(n, ast.For):
                for nn in ast.walk(n.target):
                    if isinstance(nn, ast.Name):
                        local.add(nn.id)
            elif isinstance(n, ast.comprehension):
                for nn in ast.walk(n.target):
                    if isinstance(nn, ast.Name):
                        local.add(nn.id)
        for n in ast.walk(fn):
            if isinstance(n, (ast.Global, ast.Nonlocal)):
                self._flag(n, "tracer-leak",
                           "global/nonlocal write inside a jit-traced "
                           "function")
            elif isinstance(n, ast.Assign):
                for t in n.targets:
                    if isinstance(t, ast.Attribute) and \
                            isinstance(t.value, ast.Name) and \
                            t.value.id == "self":
                        self._flag(n, "tracer-leak",
                                   f"assignment to self.{t.attr} inside a "
                                   "jit-traced function leaks a tracer "
                                   "into host state")
            elif isinstance(n, ast.Call):
                d = _dotted(n.func)
                if d and "." in d:
                    recv, tail = d.rsplit(".", 1)
                    if tail in ("append", "extend", "add") and \
                            "." not in recv and recv not in local and \
                            recv != "self":
                        self._flag(n, "tracer-leak",
                                   f"mutation of closure/global container "
                                   f"{recv!r} inside a jit-traced function")

    # ------------------------------------------------------------ finalize

    def finish(self) -> None:
        if self._calls_subscribe and not self.feed_home and \
                not self._refs_columnar_consumer:
            self.findings.append(Finding(
                self.path, 1, "feed-columnar",
                "module subscribes to the decided feed but never drains "
                "it through DecidedTap/pop_ready — per-cell consumption "
                "re-creates the fan-out cost the columnar feed removed"))


# ------------------------------------------------------------------ driver


def lint_file(path: str, relpath: str | None = None) -> list[Finding]:
    with open(path, encoding="utf-8") as f:
        source = f.read()
    return lint_source(source, path, relpath or path)


def lint_source(source: str, path: str,
                relpath: str | None = None) -> list[Finding]:
    findings: list[Finding] = []
    sups = _collect_suppressions(source, path, findings)
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        findings.append(Finding(path, e.lineno or 0, "bad-suppression",
                                f"file does not parse: {e.msg}"))
        return findings
    v = _FileLint(path, (relpath or path).replace(os.sep, "/"), tree)
    v.visit(tree)
    v.finish()
    findings.extend(v.findings)

    # Apply suppressions: same line, or a comment block directly above —
    # a suppression line covers everything down through its comment
    # block to the first source line below it (justifications are
    # encouraged to be multi-line).
    lines = source.splitlines()

    def comment_only(ln: int) -> bool:
        return 1 <= ln <= len(lines) and lines[ln - 1].lstrip().startswith("#")

    for f in findings:
        if f.rule in ("bad-suppression",):
            continue
        candidates = [f.line]
        ln = f.line - 1
        while comment_only(ln):
            candidates.append(ln)
            if ln in sups:
                break
            ln -= 1
        candidates.append(ln)  # first non-comment line above (same-line tail)
        for ln in candidates:
            s = sups.get(ln)
            if s and ("*" in s.rules or f.rule in s.rules):
                f.suppressed = True
                s.used = True
                break
    for s in sups.values():
        # Suppressions naming any whole-program rule are consan's to
        # account for — this per-file pass cannot see whether an
        # interprocedural finding matches them.
        if not s.used and not (s.rules & set(WHOLE_PROGRAM_RULES)):
            findings.append(Finding(
                path, s.line, "unused-suppression",
                f"suppression for {sorted(s.rules)} matches no finding"))
    return findings


def iter_py_files(paths: list[str]):
    for p in paths:
        if os.path.isfile(p):
            yield p
        else:
            for root, dirs, files in os.walk(p):
                dirs[:] = [d for d in dirs
                           if d not in ("__pycache__", ".git", "build")]
                for fn in sorted(files):
                    if fn.endswith(".py"):
                        yield os.path.join(root, fn)


def lint_paths(paths: list[str]) -> list[Finding]:
    out: list[Finding] = []
    for f in iter_py_files(paths):
        out.extend(lint_file(f))
    return out
