"""Explicit-collective building blocks (shard_map + psum/all_gather).

The auto-sharded kernel (parallel/mesh.py) lets XLA place the collectives;
these are the same primitives written explicitly with `shard_map`, for the
places where manual placement beats the compiler and as the reference
implementation of the communication pattern:

  - `quorum_counts`: each device holds a (local peers)-slice of per-peer
    boolean votes; the majority check is a psum over the 'p' axis — riding
    ICI, this is the reference's "count acks > npeers/2"
    (`paxos/paxos.go:181,267`) as one collective.
  - `exchange_peer_axis`: materialize the (src peer, dst peer) exchange matrix
    from a peer-sharded message vector — an all_gather over 'p', i.e. the
    kernel's message fan-out without ever leaving the device fabric.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

try:
    from jax import shard_map  # jax >= 0.8
except ImportError:  # the version-compat fallback mesh.py also carries
    from jax.experimental.shard_map import shard_map


def quorum_counts(votes: jnp.ndarray, mesh: Mesh) -> jnp.ndarray:
    """votes: (G, I, P) bool, sharded over ('g','i','p').  Returns (G, I)
    int32 vote totals, computed with an explicit psum over the peer axis."""

    def local(v):
        part = v.sum(-1).astype(jnp.int32)  # local peers only
        return jax.lax.psum(part, axis_name="p")

    fn = shard_map(
        local,
        mesh=mesh,
        in_specs=P("g", "i", "p"),
        out_specs=P("g", "i"),
    )
    return fn(votes)


def majority(votes: jnp.ndarray, npeers: int, mesh: Mesh) -> jnp.ndarray:
    """(G, I) bool: strict majority of npeers voted yes."""
    return quorum_counts(votes, mesh) * 2 > npeers


def exchange_peer_axis(msgs: jnp.ndarray, mesh: Mesh) -> jnp.ndarray:
    """msgs: (G, I, P) values 'sent' by each peer, sharded over 'p'.
    Returns (G, I, P, P) where [..., src, dst] replicates each source's
    message to every destination — an all_gather over the peer axis followed
    by a broadcast, the tensor form of sendPrepareToAll's fan-out
    (`paxos/paxos.go:161-190`)."""

    def local(m):
        allm = jax.lax.all_gather(m, axis_name="p", axis=2, tiled=True)  # (G,I,P)
        # dst axis stays local: each device holds its slice of destinations.
        loc = m.shape[2]
        return jnp.broadcast_to(
            allm[:, :, :, None], (*allm.shape, loc)
        )

    fn = shard_map(
        local,
        mesh=mesh,
        in_specs=P("g", "i", "p"),
        out_specs=P("g", "i", None, "p"),
    )
    return fn(msgs)
