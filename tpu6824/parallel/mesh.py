"""Device-mesh construction and sharding specs for the consensus kernel.

The distribution model (SURVEY §2.3): the reference's N-process Unix-socket
topology becomes axes of one device mesh —
  - 'g' (groups)    ≈ data parallelism: independent Paxos groups in lanes;
  - 'i' (instances) ≈ sequence parallelism: the sliding window of log slots;
  - 'p' (peers)     ≈ tensor parallelism: the replica axis; quorum counting
                      reduces over it, which XLA lowers to psum over ICI when
                      'p' spans devices.
Multi-host scale-out uses the same named axes over a process mesh (DCN
between hosts, ICI within) — no code change, just a bigger mesh.

Shardings are annotated with NamedSharding + jit; XLA inserts the collectives
(all-reduces for the sum/max over 'p', all-gathers where the (p, q) exchange
matrices need both axes) — nothing here hand-schedules communication.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpu6824.core.kernel import PaxosState, paxos_step


def _shard_map(local, **kw):
    """shard_map with the version-compat fallbacks (import location and
    the check_vma/check_rep kwarg rename) in ONE place."""
    try:
        from jax import shard_map  # jax >= 0.8
    except ImportError:  # pragma: no cover — older jax
        from jax.experimental.shard_map import shard_map
    try:
        return shard_map(local, check_vma=False, **kw)
    except TypeError:  # pragma: no cover — older jax
        return shard_map(local, check_rep=False, **kw)


def factor3(n: int) -> tuple[int, int, int]:
    """Split n devices into (g, i, p) mesh dims, preferring the group axis."""
    best = (n, 1, 1)
    for p in (1, 2):
        for i in (1, 2, 4):
            if n % (p * i) == 0:
                g = n // (p * i)
                best = max(best, (g, i, p), key=lambda t: (t[0] > 1, t[2], t[1]))
    g, i, p = best
    assert g * i * p == n
    return g, i, p


def make_mesh(devices=None) -> Mesh:
    devices = devices if devices is not None else jax.devices()
    g, i, p = factor3(len(devices))
    return Mesh(np.asarray(devices).reshape(g, i, p), axis_names=("g", "i", "p"))


def make_hybrid_mesh(g: int, i: int, p: int, devices=None) -> Mesh:
    """A (g, i, p) mesh that is DCN-aware on multi-slice topologies
    (the t5x `create_hybrid_device_mesh` pattern): the 'g' axis — the
    only axis with no collectives, groups never communicate — spans the
    slower DCN links between slices, while 'i'/'p' (whose quorum psum
    and window reductions ride ICI) stay within a slice.  Single-slice
    or CPU-host device sets fall back to the plain reshape `make_mesh`
    layout, which is the identity ordering there.
    """
    devices = list(devices) if devices is not None else jax.devices()
    if g * i * p != len(devices):
        raise ValueError(
            f"mesh shape (g={g}, i={i}, p={p}) needs {g * i * p} devices, "
            f"got {len(devices)}")
    slices = {getattr(d, "slice_index", 0) for d in devices}
    nslice = len(slices)
    if nslice > 1 and g % nslice == 0:
        try:
            from jax.experimental.mesh_utils import create_hybrid_device_mesh

            dm = create_hybrid_device_mesh(
                mesh_shape=(g // nslice, i, p),
                dcn_mesh_shape=(nslice, 1, 1),
                devices=devices)
            return Mesh(dm, axis_names=("g", "i", "p"))
        except Exception:  # pragma: no cover — topology probe unavailable
            pass
    return Mesh(np.asarray(devices).reshape(g, i, p),
                axis_names=("g", "i", "p"))


def fabric_mesh(ngroups: int | None = None, npeers: int | None = None,
                devices=None) -> Mesh:
    """The fabric's mesh policy, in one place: given the live device set
    and a fabric topology, pick the (g, i, p) split and build the
    (hybrid-aware) mesh.  The quorum axis 'p' spans devices only when
    the device count divides by the peer count — then majority checks
    lower to psum over ICI (the paper's headline shape, e.g. 12 devices
    × 3 peers → {g:4, i:1, p:3}); otherwise every quorum stays local and
    all devices become group lanes.  'g' shard count is capped at the
    live group count so tiny services don't pay ladder padding across
    idle devices.
    """
    devices = list(devices) if devices is not None else jax.devices()
    n = len(devices)
    p = npeers if npeers and npeers > 1 and n % npeers == 0 else 1
    g = n // p
    if ngroups and ngroups < g:
        g = ngroups
        devices = devices[:g * p]
    return make_hybrid_mesh(g, 1, p, devices)


def state_shardings(mesh: Mesh) -> PaxosState:
    """PartitionSpecs for every PaxosState leaf."""
    s3 = NamedSharding(mesh, P("g", "i", "p"))
    sdv = NamedSharding(mesh, P("g", "p", None))
    return PaxosState(
        np_=s3, na=s3, va=s3, decided=s3, active=s3, propv=s3, maxseen=s3,
        done_view=sdv,
    )


def step_args_shardings(mesh: Mesh):
    """Shardings for (link, done, key, drop_req, drop_rep)."""
    rep = NamedSharding(mesh, P())
    return (
        NamedSharding(mesh, P("g", None, None)),  # link
        NamedSharding(mesh, P("g", "p")),          # done
        rep,                                        # PRNG key
        NamedSharding(mesh, P("g", None, None)),  # drop_req
        NamedSharding(mesh, P("g", None, None)),  # drop_rep
    )


def sharded_step(mesh: Mesh):
    """jit paxos_step with explicit input/output shardings over the mesh."""
    st = state_shardings(mesh)
    args = step_args_shardings(mesh)
    return jax.jit(
        paxos_step.__wrapped__,
        in_shardings=(st, *args),
        out_shardings=None,
        donate_argnums=(0,),
    )


def sharded_step_reliable(mesh: Mesh):
    """jit paxos_step_reliable (the no-Bernoulli fast path) over the mesh —
    the reliable-network twin of `sharded_step`, so a mesh-hosted fabric
    keeps the zero-drop specialization (fabric.py's `_reliable_ok`)."""
    from tpu6824.core.kernel import paxos_step_reliable

    st = state_shardings(mesh)
    link, done = step_args_shardings(mesh)[:2]
    return jax.jit(
        paxos_step_reliable.__wrapped__,
        in_shardings=(st, link, done),
        out_shardings=None,
        donate_argnums=(0,),
    )


def sharded_apply_starts(mesh: Mesh):
    """jit apply_starts (dense host→device op injection) with the state
    kept in its mesh placement (reset/arm tensors replicate from host)."""
    from tpu6824.core.kernel import apply_starts

    st = state_shardings(mesh)
    gi = NamedSharding(mesh, P("g", "i"))
    gip = NamedSharding(mesh, P("g", "i", "p"))
    return jax.jit(
        apply_starts.__wrapped__,
        in_shardings=(st, gi, gip, gip),
        out_shardings=st,
    )


def sharded_apply_step_groups(mesh: Mesh):
    """The devapply kernel's stacked per-group step (`apply_step_groups`,
    devapply_kernel.py's shard_map composition hook) under the mesh: the
    leading group axis of every DevKVState leaf and of the packed op
    columns shards over 'g', and — since `_apply_cols` is per-group pure
    with no cross-group reads — GSPMD partitions the vmap with ZERO
    collectives.  Each device applies only its own groups' drains.

    Same donation contract as the single-device `apply_step_groups`:
    the stacked state is consumed, callers chain the returned one.
    """
    from tpu6824.core.devapply_kernel import DevKVState, _apply_cols

    lead = NamedSharding(mesh, P("g"))
    st = DevKVState(*([lead] * len(DevKVState._fields)))
    return jax.jit(
        jax.vmap(_apply_cols),
        in_shardings=(st, lead),
        out_shardings=(st, lead),
        donate_argnums=(0,),
    )


def place_state(state: PaxosState, mesh: Mesh) -> PaxosState:
    sh = state_shardings(mesh)
    return jax.tree.map(lambda x, s: jax.device_put(x, s), state, sh)


def sharded_step_auto(mesh: Mesh, impl: str | None = None,
                      interpret: bool | None = None):
    """Mesh-aware kernel dispatch (VERDICT r3 weak #4): the fused Pallas
    round needs the quorum ('p') and window ('i') axes LOCAL to a device
    (its quorum loop is unrolled in-register; its Done-piggyback reduces
    over the whole window — see `sharded_step_pallas`'s axis policy).  On
    any other mesh the XLA path, where the compiler inserts the psum/
    gather collectives, is the only sound choice — so kernel='pallas'
    composes with every mesh instead of relying on callers reading the
    axis policy.

    Returns (step_fn, resolved_impl): 'pallas' when the preference
    resolves to pallas AND the mesh keeps p == i == 1, else 'xla'.
    """
    from tpu6824.core.pallas_kernel import resolve_impl

    want = resolve_impl(impl)
    if want == "pallas" and pallas_mesh_ok(mesh):
        return sharded_step_pallas(mesh, interpret=interpret), "pallas"
    return sharded_step(mesh), "xla"


def sharded_cycle_pallas(mesh: Mesh, G: int, I: int, P: int,
                         interpret: bool | None = None):
    """The FLAGSHIP steady-state kernel — the fused recycle+arm+round cycle
    (`paxos_cycle_lanes`) — under a g-sharded mesh via shard_map.

    Layout: each of the mesh's n group-shards owns G/n groups as its own
    block-aligned lane state, so the global arrays are (P, n*Np_local)
    with per-shard padding (a shard's pallas grid never straddles another
    shard's cells).  Same axis policy as `sharded_step_pallas` (quorum +
    window local).  Returns (step, make_lane_shards, Np_local):

      step(l, done_view, done, key, sa, sv) -> (l', done_view', rec, msgs)
      make_lane_shards(PaxosState) -> LaneState in the sharded layout
    """
    from tpu6824.core.pallas_kernel import (
        LaneState, _block, paxos_cycle_lanes, to_lane_state,
    )

    if not pallas_mesh_ok(mesh):
        raise ValueError(
            "pallas fused cycle needs quorum + window axes local "
            f"(mesh 'p' == 'i' == 1, got {dict(mesh.shape)})")
    n = mesh.shape["g"]
    if G % n:
        raise ValueError(f"G={G} not divisible by mesh 'g'={n}")
    Gl = G // n
    _, Npl = _block(Gl * I)
    if interpret is None:
        interpret = jax.default_backend() not in ("tpu", "axon")

    from jax.sharding import PartitionSpec as P_

    def make_lane_shards(state) -> LaneState:
        """(G, I, P) PaxosState -> per-shard-padded sharded LaneState."""
        shards = [
            to_lane_state(jax.tree.map(lambda a: a[s * Gl:(s + 1) * Gl],
                                       state))
            for s in range(n)
        ]
        glob = LaneState(*[jnp.concatenate([getattr(s, f) for s in shards],
                                           axis=1)
                           for f in LaneState._fields])
        return jax.tree.map(
            lambda a: jax.device_put(a, NamedSharding(mesh, P_(None, "g"))),
            glob)

    lane_spec = LaneState(*([P_(None, "g")] * len(LaneState._fields)))
    dv_spec = P_("g", None, None)

    def local(l, done_view, done, key, sa, sv):
        key = jax.random.fold_in(key, jax.lax.axis_index("g"))
        l2, dv2, rec, msgs = paxos_cycle_lanes(
            l, done_view, done, key, sa, sv,
            G=Gl, I=I, mode="reliable", interpret=interpret)
        return l2, dv2, rec, msgs[None]

    f = _shard_map(local, mesh=mesh,
                   in_specs=(lane_spec, dv_spec, P_("g", None), P_(),
                             P_(None, "g"), P_(None, "g")),
                   out_specs=(lane_spec, dv_spec, P_(None, "g"), P_("g")))

    @jax.jit
    def step(l, done_view, done, key, sa, sv):
        if l.np_.shape[0] != P:
            raise ValueError(
                f"lane state has {l.np_.shape[0]} peers, expected {P}")
        l2, dv2, rec, msgs = f(l, done_view, done, key, sa, sv)
        return l2, dv2, rec, msgs.sum().astype(jnp.int32)

    return step, make_lane_shards, Npl


def pallas_mesh_ok(mesh: Mesh) -> bool:
    """The ONE statement of the fused round's axis policy: quorum ('p')
    and window ('i') must be device-local.  `sharded_step_auto` consults
    it to dispatch; `sharded_step_pallas` enforces it with a ValueError."""
    return mesh.shape["p"] == 1 and mesh.shape["i"] == 1


def sharded_step_pallas(mesh: Mesh, interpret: bool | None = None):
    """The fused Pallas round under the mesh, via shard_map around
    pallas_call — each device runs the single-HBM-round-trip kernel on its
    local shard of the cell universe.

    Axis policy (and the recorded justification for `sharded_step`'s XLA
    default on other mesh shapes, VERDICT r2 #7):
      - 'g' (groups) shards freely — groups never communicate, so the fused
        kernel runs unmodified per shard;
      - 'p' (peers) must be LOCAL: the kernel unrolls the quorum loop
        in-register; spanning 'p' across devices would need remote DMA
        inside the fused round, abandoning its one-HBM-round-trip design.
        On p>1 meshes XLA's collective insertion (sharded_step) is the
        right tool;
      - 'i' (instances) must be LOCAL here because the Done-piggyback
        reduces over the whole window per group (done_view would diverge
        across i-shards); sharded_step handles i>1 meshes.

    Per-shard PRNG: the key is folded with the shard's 'g' coordinate, so
    shards draw independent delivery masks (distribution-identical to, but
    not bit-identical with, the unsharded path).
    """
    from tpu6824.core.kernel import StepIO
    from tpu6824.core.pallas_kernel import paxos_step_pallas

    if not pallas_mesh_ok(mesh):
        raise ValueError(
            "pallas sharded step needs quorum + window axes local "
            f"(mesh 'p' == 'i' == 1, got {dict(mesh.shape)}); "
            "use sharded_step (XLA) for such meshes")
    if interpret is None:
        interpret = jax.default_backend() not in ("tpu", "axon")

    s3 = P("g", None, None)
    st_spec = PaxosState(np_=s3, na=s3, va=s3, decided=s3, active=s3,
                         propv=s3, maxseen=s3, done_view=s3)
    # proto is (G, NPROTO) per-group event totals: shards cleanly over
    # 'g' (groups never communicate, and this mesh keeps 'p'/'i' local so
    # each shard's per-group sums are already complete).
    io_spec = StepIO(decided=s3, done_view=s3, touched=s3, msgs=P("g"),
                     proto=P("g", None))

    def local(state, link, done, key, drop_req, drop_rep):
        key = jax.random.fold_in(key, jax.lax.axis_index("g"))
        st, io = paxos_step_pallas(state, link, done, key, drop_req,
                                   drop_rep, interpret=interpret)
        return st, io._replace(msgs=io.msgs[None])

    # varying-mesh-axes checking can't see through pallas_call's
    # ShapeDtypeStructs; _shard_map disables it across jax versions.
    f = _shard_map(local, mesh=mesh,
                   in_specs=(st_spec, P("g", None, None), P("g", None), P(),
                             P("g", None, None), P("g", None, None)),
                   out_specs=(st_spec, io_spec))

    @jax.jit
    def step(state, link, done, key, drop_req, drop_rep):
        st, io = f(state, link, done, key, drop_req, drop_rep)
        return st, io._replace(msgs=io.msgs.sum().astype(jnp.int32))

    return step
