"""Device-mesh construction and sharding specs for the consensus kernel.

The distribution model (SURVEY §2.3): the reference's N-process Unix-socket
topology becomes axes of one device mesh —
  - 'g' (groups)    ≈ data parallelism: independent Paxos groups in lanes;
  - 'i' (instances) ≈ sequence parallelism: the sliding window of log slots;
  - 'p' (peers)     ≈ tensor parallelism: the replica axis; quorum counting
                      reduces over it, which XLA lowers to psum over ICI when
                      'p' spans devices.
Multi-host scale-out uses the same named axes over a process mesh (DCN
between hosts, ICI within) — no code change, just a bigger mesh.

Shardings are annotated with NamedSharding + jit; XLA inserts the collectives
(all-reduces for the sum/max over 'p', all-gathers where the (p, q) exchange
matrices need both axes) — nothing here hand-schedules communication.
"""

from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpu6824.core.kernel import PaxosState, paxos_step


def factor3(n: int) -> tuple[int, int, int]:
    """Split n devices into (g, i, p) mesh dims, preferring the group axis."""
    best = (n, 1, 1)
    for p in (1, 2):
        for i in (1, 2, 4):
            if n % (p * i) == 0:
                g = n // (p * i)
                best = max(best, (g, i, p), key=lambda t: (t[0] > 1, t[2], t[1]))
    g, i, p = best
    assert g * i * p == n
    return g, i, p


def make_mesh(devices=None) -> Mesh:
    devices = devices if devices is not None else jax.devices()
    g, i, p = factor3(len(devices))
    return Mesh(np.asarray(devices).reshape(g, i, p), axis_names=("g", "i", "p"))


def state_shardings(mesh: Mesh) -> PaxosState:
    """PartitionSpecs for every PaxosState leaf."""
    s3 = NamedSharding(mesh, P("g", "i", "p"))
    sdv = NamedSharding(mesh, P("g", "p", None))
    return PaxosState(
        np_=s3, na=s3, va=s3, decided=s3, active=s3, propv=s3, maxseen=s3,
        done_view=sdv,
    )


def step_args_shardings(mesh: Mesh):
    """Shardings for (link, done, key, drop_req, drop_rep)."""
    rep = NamedSharding(mesh, P())
    return (
        NamedSharding(mesh, P("g", None, None)),  # link
        NamedSharding(mesh, P("g", "p")),          # done
        rep,                                        # PRNG key
        NamedSharding(mesh, P("g", None, None)),  # drop_req
        NamedSharding(mesh, P("g", None, None)),  # drop_rep
    )


def sharded_step(mesh: Mesh):
    """jit paxos_step with explicit input/output shardings over the mesh."""
    st = state_shardings(mesh)
    args = step_args_shardings(mesh)
    return jax.jit(
        paxos_step.__wrapped__,
        in_shardings=(st, *args),
        out_shardings=None,
        donate_argnums=(0,),
    )


def place_state(state: PaxosState, mesh: Mesh) -> PaxosState:
    sh = state_shardings(mesh)
    return jax.tree.map(lambda x, s: jax.device_put(x, s), state, sh)
