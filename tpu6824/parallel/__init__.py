from tpu6824.parallel.mesh import (  # noqa: F401
    make_mesh,
    state_shardings,
    sharded_step,
    step_args_shardings,
)
