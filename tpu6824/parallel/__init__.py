from tpu6824.parallel.mesh import (  # noqa: F401
    make_mesh,
    state_shardings,
    sharded_step,
    sharded_step_auto,
    step_args_shardings,
)
