"""Multi-host scale-out: process meshes over ICI + DCN.

The reference scales by adding OS processes connected over Unix sockets
(every `StartServer` in §L3 boots another process on the same machine).  The
TPU-native equivalent is a **process mesh**: each host contributes its local
devices, `jax.distributed.initialize` glues the processes into one logical
runtime, and the same `('g', 'i', 'p')` mesh axes from `parallel/mesh.py`
span all hosts — collectives ride ICI within a host/slice and DCN between
hosts, inserted by XLA from the same NamedShardings (SURVEY §2.3: "multi-host
scale-out uses the same collectives over DCN with a process mesh").

Axis placement policy (the scaling-book recipe — bandwidth-hungry axes on
the fastest interconnect):

  - 'p' (peers/quorum) reduces every step — it must NEVER span DCN.
  - 'i' (instance window) exchanges nothing across itself; safe anywhere.
  - 'g' (groups) is embarrassingly parallel — independent Paxos groups
    never communicate, so 'g' is the ONLY axis laid across hosts.

`arrange_for_hosts` enforces exactly that: the device array is built so the
host boundary falls on the leading 'g' axis, and 'i'/'p' tile each host's
local devices.  This is pure layout logic (testable without hardware);
`init_multihost` is the thin runtime glue.
"""

from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh

from tpu6824.parallel.mesh import factor3


def init_multihost(coordinator_address: str | None = None,
                   num_processes: int | None = None,
                   process_id: int | None = None) -> None:
    """Join this host into the process mesh — the analog of a reference
    server process binding its Unix socket and learning its peers[] list
    (`paxos/paxos.go:488-557` takes `peers, me`).  Here: one call per host,
    all devices become visible in `jax.devices()`, and every host must then
    build the SAME mesh (same device order) before running the same jitted
    step.  No-op when the process runtime is already initialized (jax
    raises on double-initialize)."""
    if jax.distributed.is_initialized():
        return
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


def group_by_process(devices) -> dict[int, list]:
    """Bucket devices by owning process (host), preserving order."""
    by_proc: dict[int, list] = {}
    for d in devices:
        by_proc.setdefault(d.process_index, []).append(d)
    return by_proc


def arrange_for_hosts(devices) -> np.ndarray:
    """Arrange devices into a (g, i, p) array whose host boundaries fall on
    the leading 'g' axis only.

    Every host must contribute the same number of devices (the usual TPU
    pod/slice shape); 'i' and 'p' factor each host's local device count, and
    hosts stack along 'g'.  Raises ValueError on ragged contributions."""
    by_proc = group_by_process(devices)
    counts = {len(v) for v in by_proc.values()}
    if len(counts) != 1:
        raise ValueError(f"ragged device counts per host: "
                         f"{ {k: len(v) for k, v in by_proc.items()} }")
    (per_host,) = counts
    gl, il, pl = factor3(per_host)  # local split; hosts multiply 'g'
    stacked = [
        np.asarray(by_proc[pid], dtype=object).reshape(gl, il, pl)
        for pid in sorted(by_proc)
    ]
    return np.concatenate(stacked, axis=0)


def make_multihost_mesh(devices=None) -> Mesh:
    """The multi-host counterpart of `mesh.make_mesh`: same axis names, so
    `state_shardings` / `sharded_step` work unchanged — a bigger mesh is the
    whole upgrade, exactly as promised in mesh.py's module docstring."""
    devices = list(devices if devices is not None else jax.devices())
    return Mesh(arrange_for_hosts(devices), axis_names=("g", "i", "p"))


def dcn_safe(mesh: Mesh) -> bool:
    """True iff no quorum ('p') or window ('i') neighbor pair crosses a host
    boundary — i.e. every step's reduce/exchange traffic stays on ICI and
    only the never-communicating 'g' axis spans DCN.  Cheap static check to
    run after mesh construction on a new topology."""
    arr = mesh.devices
    for axis in (1, 2):  # 'i', 'p'
        a = np.moveaxis(arr, axis, 0)
        first = np.vectorize(lambda d: d.process_index)(a[0])
        for sl in a[1:]:
            if (np.vectorize(lambda d: d.process_index)(sl) != first).any():
                return False
    return True
