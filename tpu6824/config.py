"""Framework configuration.

The reference has no config system — compile-time consts (`NShards=10`
`shardmaster/common.go:35`, `PingInterval/DeadPings` `viewservice/common.go:
43-48`, `FilterLife` `pbservice/server.go:23`) plus argv flags in the main/
daemons (`main/diskvd.go:39-63`).  SURVEY §5 calls for a real config layer:
fabric geometry, mesh shape, backend selection, fault-injection rates —
loadable from env / JSON and passable to every constructor.
"""

from __future__ import annotations

import dataclasses
import json
import os


@dataclasses.dataclass
class FabricConfig:
    """Geometry + behavior of the consensus fabric."""

    ngroups: int = 1
    npeers: int = 3
    ninstances: int = 64
    seed: int = 0
    auto_step: bool = True
    step_sleep: float = 0.0
    # step kernel: "xla" (fused-by-compiler, kernel.py) or "pallas"
    # (hand-fused round, pallas_kernel.py); None → $TPU6824_KERNEL,
    # else pallas on TPU / xla elsewhere
    kernel: str | None = None
    # reference accept-loop fault rates (paxos/paxos.go:528-544)
    unreliable_req_drop: float = 0.10
    unreliable_rep_drop: float = 0.20
    # pipelined clock (ISSUE 1): kernel micro-steps fused per device
    # dispatch (lax.scan in the step jit) and how many dispatches the
    # free-running clock keeps in flight (2 = double buffering).  None →
    # $TPU6824_CLOCK_STEPS_PER_DISPATCH / $TPU6824_PIPELINE_DEPTH →
    # fabric defaults (1 / 2).
    steps_per_dispatch: int | None = None
    pipeline_depth: int | None = None


@dataclasses.dataclass
class MeshConfig:
    """Device-mesh axes for the sharded step: g=group/data, i=instance/
    sequence, p=peer/tensor parallelism (tpu6824/parallel/mesh.py)."""

    g: int = 1
    i: int = 1
    p: int = 1

    @property
    def ndevices(self) -> int:
        return self.g * self.i * self.p


@dataclasses.dataclass
class Config:
    backend: str = "auto"  # auto | tpu | cpu
    fabric: FabricConfig = dataclasses.field(default_factory=FabricConfig)
    mesh: MeshConfig = dataclasses.field(default_factory=MeshConfig)

    # ------------------------------------------------------------ loading

    @classmethod
    def from_dict(cls, d: dict) -> "Config":
        return cls(
            backend=d.get("backend", "auto"),
            fabric=FabricConfig(**d.get("fabric", {})),
            mesh=MeshConfig(**d.get("mesh", {})),
        )

    @classmethod
    def from_json(cls, path: str) -> "Config":
        with open(path) as f:
            return cls.from_dict(json.load(f))

    @classmethod
    def from_env(cls, prefix: str = "TPU6824_") -> "Config":
        """TPU6824_CONFIG=/path.json wins; otherwise individual overrides
        like TPU6824_BACKEND / TPU6824_NGROUPS / TPU6824_NPEERS /
        TPU6824_NINSTANCES / TPU6824_MESH=g,i,p."""
        path = os.environ.get(prefix + "CONFIG")
        cfg = cls.from_json(path) if path else cls()
        if prefix + "BACKEND" in os.environ:
            cfg.backend = os.environ[prefix + "BACKEND"]
        for name in ("ngroups", "npeers", "ninstances", "seed"):
            key = prefix + name.upper()
            if key in os.environ:
                setattr(cfg.fabric, name, int(os.environ[key]))
        for name, key in (("steps_per_dispatch",
                           prefix + "CLOCK_STEPS_PER_DISPATCH"),
                          ("pipeline_depth", prefix + "PIPELINE_DEPTH")):
            if key in os.environ:
                setattr(cfg.fabric, name, int(os.environ[key]))
        if prefix + "MESH" in os.environ:
            g, i, p = (int(x) for x in os.environ[prefix + "MESH"].split(","))
            cfg.mesh = MeshConfig(g, i, p)
        return cfg

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    # ------------------------------------------------------------ apply

    def select_backend(self) -> str:
        """Resolve 'auto' → cpu/tpu based on what jax actually offers."""
        if self.backend != "auto":
            return self.backend
        import jax

        try:
            return jax.devices()[0].platform
        except RuntimeError:
            return "cpu"

    def make_fabric(self):
        from tpu6824.core.fabric import PaxosFabric

        f = self.fabric
        return PaxosFabric(
            ngroups=f.ngroups, npeers=f.npeers, ninstances=f.ninstances,
            seed=f.seed, auto_step=f.auto_step, step_sleep=f.step_sleep,
            kernel=f.kernel, unreliable_req_drop=f.unreliable_req_drop,
            unreliable_rep_drop=f.unreliable_rep_drop,
            steps_per_dispatch=f.steps_per_dispatch,
            pipeline_depth=f.pipeline_depth,
        )
