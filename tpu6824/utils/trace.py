"""Tracing / observability.

The reference's tracing is a compile-time `Debug` const + `DPrintf` per
package (`paxos/paxos.go:35-40`, `kvpaxos/server.go:18-23`, ...).  SURVEY §5
says the TPU framework should do better: env-gated structured tracing plus a
per-kernel-step event log with decided/sec counters.

- `dprintf(tag, fmt, ...)` — per-subsystem debug logging, enabled by
  TPU6824_DEBUG="paxos,kvpaxos" or "all" (runtime, not compile-time).
- `EventLog` — bounded ring of (ts, tag, payload) records with named
  counters; the fabric keeps one and exposes `stats()`.  Ring overflow
  is COUNTED (`counters()["dropped"]`), never silent; capacity defaults
  from TPU6824_EVENTLOG_CAP.  With `registry_prefix`, every bump is
  mirrored into the process-global tpuscope metrics registry
  (`tpu6824.obs.metrics`) so one `snapshot()` spans all components.
"""

from __future__ import annotations

import collections
import os
import sys
import threading
import time

from tpu6824.obs import metrics as _metrics

def _tags() -> set[str]:
    # Re-read every call so a long-lived daemon can have tags toggled at
    # runtime (via set_debug_tags or by mutating os.environ) — genuinely
    # "runtime, not compile-time", unlike the reference's Debug const.
    raw = os.environ.get("TPU6824_DEBUG", "")
    return {t.strip() for t in raw.split(",") if t.strip()}


def set_debug_tags(*tags: str) -> None:
    """Enable dprintf for the given subsystem tags ('all' for everything)."""
    os.environ["TPU6824_DEBUG"] = ",".join(tags)


def dprintf(tag: str, fmt: str, *args) -> None:
    """DPrintf analog: prints only when `tag` (or 'all') is enabled."""
    tags = _tags()
    if "all" in tags or tag in tags:
        msg = fmt % args if args else fmt
        print(f"[{tag} {time.monotonic():.3f}] {msg}", file=sys.stderr, flush=True)


class EventLog:
    """Thread-safe bounded event ring + monotonic counters.

    `capacity=None` reads TPU6824_EVENTLOG_CAP (default 4096) at
    construction.  A full ring drops the oldest record AND bumps the
    `dropped` counter — surfaced through `counters()` and the fabric's
    `stats()["events_dropped"]` (no silent caps)."""

    def __init__(self, capacity: int | None = None,
                 registry_prefix: str | None = None):
        if capacity is None:
            capacity = int(os.environ.get("TPU6824_EVENTLOG_CAP", 4096))
        self._cap = capacity
        self._prefix = registry_prefix
        # Ring-overflow gauge name (e.g. `fabric.events.dropped`): the
        # watchdog's dropped-climbing rule reads this, so overflow is
        # visible as a SERIES, not only a counter buried in stats().
        # Written via the registry's dynamic-name path (set_gauge) —
        # the name is data here, like the bump() mirror below.
        self._g_dropped = (f"{registry_prefix}.events.dropped"
                           if registry_prefix is not None else None)
        self._ring: collections.deque = collections.deque(maxlen=capacity)
        self._counters: collections.Counter = collections.Counter()
        self._mu = threading.Lock()
        self._t0 = time.monotonic()
        self._rate_snap: tuple[float, dict] = (self._t0, {})

    def record(self, tag: str, **payload) -> None:
        dropped = None
        with self._mu:
            if len(self._ring) == self._cap:
                self._counters["dropped"] += 1
                dropped = self._counters["dropped"]
            self._ring.append((time.monotonic(), tag, payload))
        if dropped is not None and self._g_dropped is not None:
            # Mirror outside self._mu (registry takes its own lock);
            # only paid in the overflow regime the gauge exists for.
            _metrics.set_gauge(self._g_dropped, dropped)

    def bump(self, counter: str, n: int = 1) -> None:
        with self._mu:
            self._counters[counter] += n
        if self._prefix is not None:
            # Mirror into the tpuscope registry OUTSIDE self._mu (the
            # registry takes its own lock; bumps are batch-granular).
            _metrics.inc(f"{self._prefix}.{counter}", n)

    def events(self, tag: str | None = None) -> list:
        with self._mu:
            evs = list(self._ring)
        return evs if tag is None else [e for e in evs if e[1] == tag]

    def counters(self) -> dict[str, int]:
        with self._mu:
            return dict(self._counters)

    def rates(self) -> dict[str, float]:
        """Counters per second over the interval since the previous `rates()`
        call (since creation, on the first call) — a live rate for pollers,
        not a lifetime average that decays with uptime."""
        now = time.monotonic()
        cur = self.counters()
        with self._mu:
            prev_t, prev = self._rate_snap
            self._rate_snap = (now, cur)
        dt = max(now - prev_t, 1e-9)
        return {k: (v - prev.get(k, 0)) / dt for k, v in cur.items()}
