"""Tracing / observability.

The reference's tracing is a compile-time `Debug` const + `DPrintf` per
package (`paxos/paxos.go:35-40`, `kvpaxos/server.go:18-23`, ...).  SURVEY §5
says the TPU framework should do better: env-gated structured tracing plus a
per-kernel-step event log with decided/sec counters.

- `dprintf(tag, fmt, ...)` — per-subsystem debug logging, enabled by
  TPU6824_DEBUG="paxos,kvpaxos" or "all" (runtime, not compile-time).
- `EventLog` — bounded ring of (ts, tag, payload) records with named
  counters; the fabric keeps one and exposes `stats()`.
"""

from __future__ import annotations

import collections
import os
import sys
import threading
import time

_enabled: set[str] | None = None
_lock = threading.Lock()


def _tags() -> set[str]:
    global _enabled
    if _enabled is None:
        raw = os.environ.get("TPU6824_DEBUG", "")
        _enabled = {t.strip() for t in raw.split(",") if t.strip()}
    return _enabled


def dprintf(tag: str, fmt: str, *args) -> None:
    """DPrintf analog: prints only when `tag` (or 'all') is enabled."""
    tags = _tags()
    if "all" in tags or tag in tags:
        msg = fmt % args if args else fmt
        print(f"[{tag} {time.monotonic():.3f}] {msg}", file=sys.stderr, flush=True)


class EventLog:
    """Thread-safe bounded event ring + monotonic counters."""

    def __init__(self, capacity: int = 4096):
        self._ring: collections.deque = collections.deque(maxlen=capacity)
        self._counters: collections.Counter = collections.Counter()
        self._mu = threading.Lock()
        self._t0 = time.monotonic()

    def record(self, tag: str, **payload) -> None:
        with self._mu:
            self._ring.append((time.monotonic(), tag, payload))

    def bump(self, counter: str, n: int = 1) -> None:
        with self._mu:
            self._counters[counter] += n

    def events(self, tag: str | None = None) -> list:
        with self._mu:
            evs = list(self._ring)
        return evs if tag is None else [e for e in evs if e[1] == tag]

    def counters(self) -> dict[str, int]:
        with self._mu:
            return dict(self._counters)

    def rates(self) -> dict[str, float]:
        """Counters per second since creation."""
        dt = max(time.monotonic() - self._t0, 1e-9)
        return {k: v / dt for k, v in self.counters().items()}
