"""Crash sink for daemon threads.

Every daemon thread in the system (`threading.Thread(daemon=True)`) must
route its death through here: a daemon that dies silently is how a
replica stops Done()-ing and jams a whole group's instance window with
no symptom but clerk timeouts.  The Go reference gets a crashed
goroutine's stack on stderr for free; this is the equivalent, with the
record additionally surfaced in `PaxosFabric.stats()["health"]` so a
harness (or the nemesis failure artifact) can assert on it.

Two idioms, both recognized by the `daemon-crash-sink` tpusan lint rule:

  - `threading.Thread(target=crashsink.guarded(self._loop, "kvpaxos-driver"),
     daemon=True)` — wraps the target; an escaping exception is recorded
     (and re-raised, so the interpreter's threading excepthook still
     prints it).
  - a run loop that survives per-iteration failures calls
    `crashsink.record(name, exc, fatal=False)` from its own narrow
    handler and keeps driving.

The sink is process-global and append-only; `clear()` exists for tests.
"""

from __future__ import annotations

import threading
import time
import traceback

_MAX_RECORDS = 256  # bound memory under a crash-looping thread

_lock = threading.Lock()
_records: list[dict] = []
_dropped = 0

# Flush hooks (ISSUE 20): callables `fn(rec)` invoked after each record
# lands — how blackbox gets crash evidence onto disk AT RECORD TIME
# instead of whenever the next poller asks.  Bounded: one deduplicated
# hook per consumer.  Hook failures are swallowed and counted (a hook
# must never recurse into record(), so no re-entry here).
_flush_hooks: list = []
_hook_errors = 0


def add_flush_hook(fn) -> None:
    with _lock:
        if fn not in _flush_hooks:
            _flush_hooks.append(fn)


def remove_flush_hook(fn) -> None:
    with _lock:
        if fn in _flush_hooks:
            _flush_hooks.remove(fn)


def record(name: str, exc: BaseException, *, fatal: bool = True) -> None:
    """Record one thread crash.  `fatal=True` means the thread is dying;
    `fatal=False` is a survived per-iteration failure in a keep-driving
    loop (still worth surfacing: a driver crash-looping at 50Hz is a bug
    even if every individual iteration "recovers")."""
    global _dropped
    with _lock:
        if len(_records) >= _MAX_RECORDS:
            # Bound check BEFORE formatting: a crash-looping thread must
            # not pay a full traceback.format_exception per dropped
            # record — the cap exists exactly for that degenerate case.
            _dropped += 1
            return
    rec = {
        "thread": name,
        "error": repr(exc),
        "traceback": "".join(
            traceback.format_exception(type(exc), exc, exc.__traceback__)),
        "fatal": fatal,
        "t": time.monotonic(),
    }
    with _lock:
        if len(_records) >= _MAX_RECORDS:
            _dropped += 1
            return
        _records.append(rec)
        hooks = list(_flush_hooks)
    # Hooks run OUTSIDE _lock (a hook that records telemetry must not
    # serialize against concurrent crashes) and never raise — a broken
    # flush path must not mask the crash being recorded.
    global _hook_errors
    for fn in hooks:
        try:
            fn(rec)
        except Exception:  # noqa: BLE001 — counted, never propagated
            _hook_errors += 1


def crashes() -> list[dict]:
    with _lock:
        return [dict(r) for r in _records]


def summary() -> dict:
    """Compact health-report form: total count + the distinct thread
    names that have crashed (fatal or not), cheap enough to embed in
    every stats() call."""
    with _lock:
        return {
            "count": len(_records) + _dropped,
            "threads": sorted({r["thread"] for r in _records}),
            "fatal": sum(1 for r in _records if r["fatal"]),
        }


def clear() -> None:
    global _dropped
    with _lock:
        _records.clear()
        _dropped = 0


def guarded(fn, name: str | None = None):
    """Wrap a daemon-thread target so an escaping exception is recorded
    before the thread dies.  The exception is re-raised: the standard
    threading excepthook still prints the stack, and tests that join()
    the thread see it gone — nothing about thread lifetime changes,
    death just stops being silent."""
    label = name or getattr(fn, "__qualname__", repr(fn))

    def _run(*args, **kwargs):
        try:
            return fn(*args, **kwargs)
        except BaseException as e:
            record(label, e)
            raise

    _run.__name__ = f"guarded[{label}]"
    return _run
