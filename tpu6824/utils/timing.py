"""Polling helpers for tests and service loops.

The reference tests poll with exponential backoff and a hard cap
(`paxos/test_test.go:51-70` waitn: 30 polls, 10ms doubling to 1s).  Service
sync loops do the same (`kvpaxos/server.go:73-77,105-109`).  `wait_until`
reproduces that rhythm for the host-side harness.
"""

import time


def wait_until(pred, timeout=10.0, initial=0.001, cap=0.1):
    """Poll `pred` with exponential backoff until it returns truthy or
    `timeout` seconds elapse.  Returns the last value of pred()."""
    deadline = time.monotonic() + timeout
    sleep = initial
    while True:
        v = pred()
        if v:
            return v
        if time.monotonic() >= deadline:
            return v
        time.sleep(sleep)
        sleep = min(sleep * 2, cap)


def backoff_sleeps(initial=0.001, cap=0.1):
    """Generator of exponentially growing sleep intervals."""
    sleep = initial
    while True:
        yield sleep
        sleep = min(sleep * 2, cap)
