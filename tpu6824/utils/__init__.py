from tpu6824.utils.errors import (  # noqa: F401
    Err,
    OK,
    ErrNoKey,
    ErrWrongGroup,
    ErrWrongServer,
    ErrNotReady,
    ErrUninitServer,
    RPCError,
)
from tpu6824.utils.timing import wait_until  # noqa: F401
