"""Error vocabulary shared by all services.

Mirrors the Err string constants scattered through the reference wire types
(`pbservice/common.go:21-47`, `kvpaxos/common.go`, `shardmaster/common.go`,
`shardkv/common.go`) — collected in one place instead of re-declared per
package.
"""

OK = "OK"
ErrNoKey = "ErrNoKey"
ErrWrongServer = "ErrWrongServer"
ErrWrongGroup = "ErrWrongGroup"
ErrNotReady = "ErrNotReady"
ErrUninitServer = "ErrUninitServer"
# txnkv (ISSUE 13): a key is locked by a prepared cross-group transaction
# — retryable, NEVER recorded in the dup filter (the client re-sends the
# same cseq once the lock releases, exactly the ErrWrongGroup contract);
# and a prepare vote of no (CAS expectation failed / deterministic
# refusal) — recorded, the transaction must abort.
ErrTxnLocked = "ErrTxnLocked"
ErrTxnAbort = "ErrTxnAbort"

Err = str


class RPCError(Exception):
    """A host-level 'call failed' — the moral equivalent of `call()` returning
    false in the reference (`lockservice/client.go:26-40`): the caller must
    assume the operation *may or may not* have executed."""
