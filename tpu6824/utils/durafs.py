"""durafs — the ONE durable-write seam, with deterministic disk faults.

Every durable write in the tree (`services/diskv.py` key/meta files,
`HostPaxosPeer(persist_dir=...)` ledger records, the fabric checkpoint
path) routes through `atomic_write()` here, which implements the full
crash-consistency discipline the reference's Lab 5 on-disk contract
implies but `diskv/server.go:92-105` only half-does:

    write tmp  →  fsync(tmp)  →  rename(tmp, path)  →  fsync(dir)

Without the tmp fsync, a crash after the rename can publish a file whose
DATA never reached the platter (the rename is durable before the
content); without the dir fsync, the rename itself can be lost.  Both
halves are exactly what the fault injector below tears.

Fault injection: a `DuraDisk` registered over a directory intercepts
every durable write under it and consults (a) a FIFO of one-shot armed
faults (the nemesis `DiskTarget` arms these from a seeded
`FaultSchedule`, so disk faults replay byte-exactly like any other
nemesis event) and (b) an optional seeded `FaultPlan` drawing per-op
faults at fixed rates.  Supported faults:

    torn           write only the first ``frac`` of the payload into the
                   tmp file, then die (DiskFault) — tmp debris remains,
                   the target file is untouched;
    enospc         the write fails up front with ENOSPC;
    fsync_lie      the write "succeeds" but NEITHER the data nor the
                   rename was synced — a later `power_crash()` reverts
                   the file to its previous durable content;
    crash_rename   data synced, rename done, dir-sync skipped, writer
                   dies — the file READS new but `power_crash()` undoes
                   the un-synced directory entry;
    lose_disk      the whole scope directory is destroyed mid-write.

`power_crash()` is the power-loss model: everything written through the
disk whose durability was a lie is rolled back to the last state that
was actually synced.  A write that completed the full discipline is
never rolled back — that asymmetry is the whole point, and the
durafault tests assert both directions.

Determinism: armed faults fire in FIFO order against the disk's
monotonically-numbered durable ops; `FaultPlan(seed, rates)` consumes a
private `random.Random(seed)` one draw per op.  Same op sequence, same
plan → identical fault placement.
"""

from __future__ import annotations

import contextlib
import errno
import os
import random
import shutil
import threading

#: Sentinel for "the path did not durably exist" in the volatile journal.
MISSING = object()

FAULT_KINDS = ("torn", "enospc", "fsync_lie", "crash_rename", "lose_disk")


class DiskFault(OSError):
    """An injected durable-write fault.  Subclasses OSError so existing
    handlers for real disk errors (ENOSPC, EIO) treat it identically —
    the injector must never need special-cased catches in product code."""

    def __init__(self, eno: int, msg: str, path: str, kind: str):
        super().__init__(eno, msg, path)
        self.kind = kind


class FaultPlan:
    """Seeded per-op fault sampler: one draw per durable op, at fixed
    per-kind rates.  `rates` maps fault kind → probability; the draws
    come off a private Random(seed), so the same op sequence replays the
    same faults."""

    def __init__(self, seed: int, rates: dict[str, float] | None = None):
        bad = set(rates or ()) - set(FAULT_KINDS)
        if bad:
            raise ValueError(f"unknown fault kinds: {sorted(bad)}")
        self.seed = seed
        self.rates = dict(rates or {})
        self._rng = random.Random(seed)

    def draw(self) -> dict | None:
        """Fault for the next durable op, or None.  ALWAYS consumes
        exactly two rng draws so fault placement is a pure function of
        the op index, not of which earlier ops faulted."""
        u, frac = self._rng.random(), self._rng.random()
        acc = 0.0
        for kind in FAULT_KINDS:
            acc += self.rates.get(kind, 0.0)
            if u < acc:
                return {"kind": kind, "frac": frac}
        return None


class DuraDisk:
    """One fault-injectable durable-write scope rooted at a directory.

    Tracks a volatile journal — for every write whose durability was
    faked (fsync_lie / crash_rename), the previous DURABLE content of
    the path — so `power_crash()` can model what a real power loss
    would do to the un-synced page cache and directory entries."""

    def __init__(self, root: str, plan: FaultPlan | None = None):
        self.root = os.path.abspath(root)
        self.plan = plan
        self._mu = threading.Lock()
        self._armed: list[dict] = []  # FIFO of one-shot faults
        self._journal: dict[str, object] = {}  # path -> prev durable bytes
        self.op_index = 0
        self.counts: dict[str, int] = {"writes": 0}
        self.lost = False

    # ------------------------------------------------------------ arming

    def arm(self, kind: str, frac: float = 0.5) -> None:
        """Queue a one-shot fault for the next durable write in this
        scope (FIFO).  This is the nemesis DiskTarget's injection point:
        the schedule event carries (kind, frac), so replay re-arms the
        identical fault at the identical event offset."""
        if kind not in FAULT_KINDS:
            raise ValueError(f"unknown fault kind {kind!r}")
        with self._mu:
            self._armed.append({"kind": kind, "frac": frac})

    def disarm(self) -> None:
        """Drop every armed-but-unfired fault (nemesis restore tail)."""
        with self._mu:
            self._armed.clear()

    def _next_fault(self) -> dict | None:
        # Callers hold self._mu.
        if self._armed:
            return self._armed.pop(0)
        if self.plan is not None:
            return self.plan.draw()
        return None

    # ------------------------------------------------------------ writes

    def atomic_write(self, path: str, data: bytes) -> None:
        path = os.path.abspath(path)
        with self._mu:
            if self.lost:
                # Lost stays lost until reset(): a writer that raced the
                # loss must not resurrect the directory with a partial
                # image a later reboot would mistake for a disk.
                raise DiskFault(errno.EIO, "durafs: disk is lost",
                                path, "lose_disk")
            self.op_index += 1
            self.counts["writes"] += 1
            fault = self._next_fault()
            kind = fault["kind"] if fault else None
            if kind:
                self.counts[kind] = self.counts.get(kind, 0) + 1
            if kind == "enospc":
                raise DiskFault(errno.ENOSPC,
                                "durafs: injected ENOSPC", path, kind)
            if kind == "lose_disk":
                self.lost = True
                self._journal.clear()
                shutil.rmtree(self.root, ignore_errors=True)
                raise DiskFault(errno.EIO, "durafs: disk lost mid-write",
                                path, kind)
            tmp = _tmp_name(path)
            if kind == "torn":
                k = int(len(data) * fault.get("frac", 0.5))
                with open(tmp, "wb") as f:
                    f.write(data[:k])
                    f.flush()
                    os.fsync(f.fileno())
                raise DiskFault(
                    errno.EIO, f"durafs: torn write at byte {k}", path, kind)
            lie = kind == "fsync_lie"
            prev = self._prev_durable_locked(path) \
                if kind in ("fsync_lie", "crash_rename") else None
            with open(tmp, "wb") as f:
                f.write(data)
                f.flush()
                if not lie:
                    os.fsync(f.fileno())
            os.replace(tmp, path)
            if lie:
                # The write "succeeded": no exception, but neither the
                # data nor the rename is durable.
                self._journal[path] = prev
                return
            if kind == "crash_rename":
                # Data synced, rename visible, dir entry NOT synced —
                # and the writer dies right here.
                self._journal[path] = prev
                raise DiskFault(
                    errno.EIO,
                    "durafs: crashed after rename, before dir fsync",
                    path, kind)
            # tpusan: ok(lock-blocking-reachable) — the dir fsync must
            # be ordered inside the disk mutation lock: releasing _mu
            # before it would let a second writer interleave between
            # rename and fsync and break the crash-atomicity contract.
            _fsync_dir(os.path.dirname(path))
            # The full discipline ran: this path's content is durable.
            self._journal.pop(path, None)

    def _prev_durable_locked(self, path: str):
        if path in self._journal:
            return self._journal[path]  # oldest durable content wins
        try:
            with open(path, "rb") as f:
                return f.read()
        except FileNotFoundError:
            return MISSING

    # ----------------------------------------------------------- crashes

    def power_crash(self) -> list[str]:
        """Model a power loss: every path whose last write skipped part
        of the sync discipline reverts to its previous durable content
        (or vanishes, if it never durably existed).  Fully-synced writes
        are untouched.  Returns the reverted paths (tests assert on
        them)."""
        with self._mu:
            reverted = []
            for path, prev in self._journal.items():
                try:
                    if prev is MISSING:
                        os.unlink(path)
                    else:
                        with open(path, "wb") as f:
                            f.write(prev)
                except OSError:
                    continue  # scope since lost / path since removed
                reverted.append(path)
            self._journal.clear()
            return sorted(reverted)

    def lose(self) -> None:
        """Destroy the scope (the harness's rmtree disk loss, routed so
        the journal cannot resurrect files into a lost disk).  Writes
        through this disk fail until `reset()` — the replaced-disk
        half of a reboot."""
        with self._mu:
            self.lost = True
            self._journal.clear()
            shutil.rmtree(self.root, ignore_errors=True)

    def reset(self) -> None:
        """Fresh-disk reset at reboot: clears the lost flag, armed-but-
        unfired faults, and the volatile journal (a new process starts
        from whatever is durably on disk, with a clean page cache)."""
        with self._mu:
            self.lost = False
            self._armed.clear()
            self._journal.clear()

    def stats(self) -> dict:
        with self._mu:
            return {"root": self.root, "ops": self.op_index,
                    "volatile": len(self._journal), "lost": self.lost,
                    "counts": dict(self.counts),
                    "armed": len(self._armed)}


# ---------------------------------------------------------------- registry

_reg_mu = threading.Lock()
_disks: dict[str, DuraDisk] = {}  # abspath root -> disk


def register(disk: DuraDisk) -> DuraDisk:
    with _reg_mu:
        _disks[disk.root] = disk
    return disk


def unregister(disk_or_root) -> None:
    root = disk_or_root.root if isinstance(disk_or_root, DuraDisk) \
        else os.path.abspath(disk_or_root)
    with _reg_mu:
        _disks.pop(root, None)


def lookup(path: str) -> DuraDisk | None:
    """The registered disk covering `path` (longest root wins)."""
    p = os.path.abspath(path)
    with _reg_mu:
        best = None
        for root, disk in _disks.items():
            if p == root or p.startswith(root + os.sep):
                if best is None or len(root) > len(best.root):
                    best = disk
        return best


@contextlib.contextmanager
def scope(root: str, plan: FaultPlan | None = None):
    """Register a DuraDisk over `root` for the duration of a with-block
    (the test-side arming surface)."""
    disk = register(DuraDisk(root, plan=plan))
    try:
        yield disk
    finally:
        unregister(disk)


# -------------------------------------------------------------- primitives


def _tmp_name(path: str) -> str:
    """Per-writer-unique scratch name.  pid+tid keeps concurrent writers
    (a rebooted server sharing a dir with the old instance's still-
    draining driver) from racing rename-vs-rename on one shared tmp —
    the pre-PR-4 test_diskv flake.  The suffix stays ".tmp" so debris
    sweeps (diskv `_load_from_disk`) and footprint probes keep matching."""
    return f"{path}.{os.getpid()}.{threading.get_ident()}.tmp"


def _fsync_dir(d: str) -> None:
    """Make a rename in `d` durable.  Directory fds are not a universal
    POSIX guarantee (and some filesystems refuse O_DIRECTORY fsync);
    failure to sync the dir is not failure to write."""
    try:
        fd = os.open(d or ".", os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def atomic_write(path: str, data: bytes) -> None:
    """THE durable write: tmp + fsync(tmp) + rename + fsync(dir).  Routes
    through the registered DuraDisk covering `path` when one exists (the
    fault-injection seam); identical discipline either way."""
    disk = lookup(path)
    if disk is not None:
        disk.atomic_write(os.path.abspath(path), data)
        return
    tmp = _tmp_name(path)
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    _fsync_dir(os.path.dirname(path))
