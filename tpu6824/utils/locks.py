"""Named, budgeted lock constructors — the product-code seam for
`tpu6824.analysis.lockwatch`.

Hot-path locks are created through `new_lock`/`new_rlock` with a name
and a hold-time budget, turning perf notes like TUNING round 7's "the
decided fan-out MUST stay columnar under the fabric lock" into an
enforced contract: under `TPU6824_SANITIZE=1` (or the `sanitize` pytest
fixture) the lock is instrumented and holding it past its budget fails
the sanitized run.  With the sanitizer off this is exactly
`threading.Lock()` / `threading.RLock()` — no wrapper, no overhead.

Import cost matters (these are constructed on every fabric/server
boot): lockwatch is stdlib-only and tiny, so importing it here is safe
even in JAX-free tooling contexts.
"""

from __future__ import annotations

from tpu6824.analysis import lockwatch

# The canonical lock hierarchy, OUTERMOST FIRST: a thread holding a lock
# may only acquire locks that appear LATER in this tuple.  One
# declaration, validated twice — statically by analysis/consan.py (every
# interprocedural acquisition edge must point forward; a named lock
# missing here is a finding) and live by lockwatch's manifest lockdep
# (a backward acquisition is an order violation the sanitize fixture
# fails on, even before any cycle closes).  Derived from the measured
# acquisition graph: the service-layer server mutexes sit above the
# engine/core leaves they call into (kvpaxos.mu → devapply.emu is the
# documented PR 15/16 order; server mu → PaxosFabric._lock is the
# start/status path; shardkv.mu → FlakyNet._lock is the transport
# bookkeeping leg), and the frontend/observability locks never nest
# with them.  New named locks MUST be slotted here at their rank.
MANIFEST: tuple[str, ...] = (
    "frontend.mirror_mu",     # engine mirror pass vs metrics RPC
    "shardkv.mu",             # shardkv server mutex
    "shardmaster.mu",         # shardmaster server mutex
    "kvpaxos.mu",             # kvpaxos server mutex
    "txnkv.inflight_mu",      # module-level inflight-txn gauge guard
    "devapply.emu",           # columnar apply-engine leaf (reentrant)
    "PaxosFabric._lock",      # fabric clock/submit core
    "FlakyNet._lock",         # transport partition/bookkeeping leaf
    "horizon.trackers_mu",    # row-count tracker registry leaf
    "txnkv.cseq_mu",          # clerk op-sequence counter leaf
)

lockwatch.set_manifest(MANIFEST)


def new_lock(name: str, hold_budget_s: float | None = None):
    """A non-reentrant lock named for sanitizer reports; `hold_budget_s`
    (None = lockwatch's DEFAULT_BUDGET_S) bounds how long any holder may
    keep it under a sanitized run."""
    return lockwatch.make_lock(name=name, hold_budget_s=hold_budget_s)


def new_rlock(name: str, hold_budget_s: float | None = None):
    """Reentrant variant of `new_lock` (RSM servers re-enter their own
    `mu` through apply → waiter-resolution paths)."""
    return lockwatch.make_rlock(name=name, hold_budget_s=hold_budget_s)
