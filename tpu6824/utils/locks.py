"""Named, budgeted lock constructors — the product-code seam for
`tpu6824.analysis.lockwatch`.

Hot-path locks are created through `new_lock`/`new_rlock` with a name
and a hold-time budget, turning perf notes like TUNING round 7's "the
decided fan-out MUST stay columnar under the fabric lock" into an
enforced contract: under `TPU6824_SANITIZE=1` (or the `sanitize` pytest
fixture) the lock is instrumented and holding it past its budget fails
the sanitized run.  With the sanitizer off this is exactly
`threading.Lock()` / `threading.RLock()` — no wrapper, no overhead.

Import cost matters (these are constructed on every fabric/server
boot): lockwatch is stdlib-only and tiny, so importing it here is safe
even in JAX-free tooling contexts.
"""

from __future__ import annotations

from tpu6824.analysis import lockwatch


def new_lock(name: str, hold_budget_s: float | None = None):
    """A non-reentrant lock named for sanitizer reports; `hold_budget_s`
    (None = lockwatch's DEFAULT_BUDGET_S) bounds how long any holder may
    keep it under a sanitized run."""
    return lockwatch.make_lock(name=name, hold_budget_s=hold_budget_s)


def new_rlock(name: str, hold_budget_s: float | None = None):
    """Reentrant variant of `new_lock` (RSM servers re-enter their own
    `mu` through apply → waiter-resolution paths)."""
    return lockwatch.make_rlock(name=name, hold_budget_s=hold_budget_s)
