"""Kernel-step profiling — the part of SURVEY §5's "do better" note that
EventLog's counters don't cover: device-level timelines.

The reference's only profiling artifact is a commented-out `runtime.GC()`
(`paxos/paxos.go-too-many-rpcs:132`).  Here the runtime exposes the JAX
profiler directly: `trace(outdir)` captures a Perfetto/TensorBoard trace
(XLA ops, fusion boundaries, HBM transfers on TPU) around any region, and
`profile_steps` wraps N fabric clock steps — the unit all consensus work
happens in."""

from __future__ import annotations

import contextlib
import os


@contextlib.contextmanager
def trace(outdir: str):
    """Capture a JAX profiler trace (viewable in Perfetto / TensorBoard)
    for the enclosed region."""
    import jax

    os.makedirs(outdir, exist_ok=True)
    jax.profiler.start_trace(outdir)
    try:
        yield outdir
    finally:
        jax.profiler.stop_trace()


def profile_steps(fabric, n: int, outdir: str) -> str:
    """Trace n fabric clock steps.  Call with the clock stopped (the traced
    region must own the stepping).  Returns outdir."""
    with trace(outdir):
        fabric.step(n)
    return outdir
