"""Kernel-step profiling — the part of SURVEY §5's "do better" note that
EventLog's counters don't cover: device-level timelines.

The reference's only profiling artifact is a commented-out `runtime.GC()`
(`paxos/paxos.go-too-many-rpcs:132`).  Here the runtime exposes the JAX
profiler directly: `trace(outdir)` captures a Perfetto/TensorBoard trace
(XLA ops, fusion boundaries, HBM transfers on TPU) around any region, and
`profile_steps` wraps N fabric clock steps — the unit all consensus work
happens in.

`PhaseProfiler` is the HOST-side counterpart: cheap wall-time accounting
for the named phases of the decided pipeline (stage → dispatch → retire →
feed → apply → notify), always on (two perf_counter_ns calls per phase per
BATCH, never per op).  The fabric owns one and surfaces it in `stats()`;
the bench service/clerk legs snapshot it so "where does a clerk op's wall
time go" is a published breakdown, not an assertion (VERDICT r5 weak #1)."""

from __future__ import annotations

import contextlib
import os
import threading
import time


@contextlib.contextmanager
def trace(outdir: str):
    """Capture a JAX profiler trace (viewable in Perfetto / TensorBoard)
    for the enclosed region."""
    import jax

    os.makedirs(outdir, exist_ok=True)
    jax.profiler.start_trace(outdir)
    try:
        yield outdir
    finally:
        jax.profiler.stop_trace()


def profile_steps(fabric, n: int, outdir: str) -> str:
    """Trace n fabric clock steps.  Call with the clock stopped (the traced
    region must own the stepping).  Returns outdir."""
    with trace(outdir):
        fabric.step(n)
    return outdir


class PhaseProfiler:
    """Thread-safe per-phase wall-time accumulator.

    Phases are recorded per batch (one `phase()` region wraps a whole
    dispatch's staging, a whole retire's device_get, a whole apply batch),
    so the overhead is O(dispatches), not O(ops).  `snapshot()` returns raw
    nanosecond/count totals so callers can diff two snapshots around a
    measurement window (the bench legs do).

    For the per-op view these aggregates cannot give — one clerk op's
    clerk→rpc→submit→dispatch→apply→reply chain against the fabric
    batches that carried it — use tpuscope (`tpu6824.obs`): with
    `TPU6824_TRACE=1` the same pipeline emits causal spans, and
    `obs.export_trace(path)` writes Chrome trace-event / Perfetto JSON
    alongside the `trace(outdir)` device traces captured here."""

    def __init__(self):
        self._mu = threading.Lock()
        self._ns: dict[str, int] = {}
        self._n: dict[str, int] = {}

    def add(self, name: str, ns: int, count: int = 1) -> None:
        with self._mu:
            self._ns[name] = self._ns.get(name, 0) + ns
            self._n[name] = self._n.get(name, 0) + count

    @contextlib.contextmanager
    def phase(self, name: str):
        t0 = time.perf_counter_ns()
        try:
            yield
        finally:
            self.add(name, time.perf_counter_ns() - t0)

    def snapshot(self) -> dict:
        """{phase: {"ns": total, "count": batches}} — raw, diffable."""
        with self._mu:
            return {k: {"ns": v, "count": self._n.get(k, 0)}
                    for k, v in self._ns.items()}

    @staticmethod
    def breakdown(after: dict, before: dict | None = None,
                  wall_seconds: float | None = None) -> dict:
        """Human/JSON view of snapshot(s): seconds + count per phase, the
        summed busy time, and — with `wall_seconds` — each phase's and the
        total's fraction of the wall clock.  On a 1-core host the gap
        `1 - total_fraction` is time spent OUTSIDE these framework phases
        (interpreter bookkeeping, GIL waits, scheduler, syscalls)."""
        out, total_ns = {}, 0
        for k, v in sorted(after.items()):
            ns = v["ns"] - (before or {}).get(k, {}).get("ns", 0)
            n = v["count"] - (before or {}).get(k, {}).get("count", 0)
            if ns <= 0 and n <= 0:
                continue
            total_ns += ns
            out[k] = {"seconds": round(ns / 1e9, 4), "count": n}
            if wall_seconds:
                out[k]["wall_fraction"] = round(ns / 1e9 / wall_seconds, 4)
        summary = {"phases": out,
                   "total_seconds": round(total_ns / 1e9, 4)}
        if wall_seconds:
            summary["wall_seconds"] = round(wall_seconds, 4)
            summary["total_wall_fraction"] = round(
                total_ns / 1e9 / wall_seconds, 4)
        return summary
