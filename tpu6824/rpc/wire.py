"""fe wire — the versioned little-endian frame layout for the clerk
frontend's batched request path (ISSUE 11, ROADMAP item 1).

One schema, two decoders: this module is the PYTHON side (encoder for
clerks, decoder for the pure-Python fallback servers), and
`tpu6824/native/fewire.h` is the byte-for-byte C++ mirror the epoll loop
decodes with — straight into preallocated int64/int32 columnar buffers,
no GIL, no Python objects (the *Paxos Made Switch-y* dataplane bet with
our native server playing the P4 switch).  Any layout change bumps
``VERSION`` **in both files** and must keep the older decoder refusing
(not mis-parsing) the newer frame.

Frames ride the existing L0 transport framing (4-byte big-endian length
prefix) and are distinguished from the classic pickled tuples by magic:
pickle frames begin with ``\\x80`` (PROTO opcode), fe frames with
``FE``.  Old pickled ``fe_batch`` / ``get`` / ``put_append`` frames stay
first-class on every server — interop both directions is a contract,
not a transition state.

Layout v1 (all integers little-endian):

  request   'F' 'E' 'B' ver |u16 flags|u16 nops| [u64 tid,u64 sid]
            then nops records: u8 kind |u64 cid|i64 cseq|u16 klen|
            u32 vlen| key bytes | value bytes
  reply     'F' 'E' 'R' ver |u16 flags|u16 nops|
            then nops records: u8 err |u32 vlen| value bytes
  error     'F' 'E' 'E' ver |u32 mlen| utf-8 message
            (maps to RPCError at the client, like a (False, msg) reply)

flags bit 0 on a request: the optional tpuscope trace context
(trace_id, span_id) follows the header — the PR-5 third frame element,
frame-scoped.  kind and err are closed enums below; err 255 is the
escape hatch (value bytes carry a pickled (err, value) pair) so exotic
service replies survive the binary path without widening the enum.
"""

from __future__ import annotations

import pickle
import struct

from tpu6824.utils.errors import OK, ErrNoKey, ErrWrongGroup, RPCError

VERSION = 1

MAGIC_BATCH = b"FEB" + bytes([VERSION])
MAGIC_REPLY = b"FER" + bytes([VERSION])
MAGIC_ERROR = b"FEE" + bytes([VERSION])

FLAG_TRACE = 1  # request flags bit 0: (trace_id, span_id) present

# Closed op-kind enum — the int32 the native decoder writes into the
# kind column.  Order is part of the schema.
KINDS = ("get", "put", "append")
KIND_CODE = {k: i for i, k in enumerate(KINDS)}

# Closed reply-err enum; 255 = pickled escape hatch.
ERRS = (OK, ErrNoKey, ErrWrongGroup)
ERR_CODE = {e: i for i, e in enumerate(ERRS)}
ERR_OTHER = 255

_HDR = struct.Struct("<4sHH")            # magic, flags, nops
_TC = struct.Struct("<QQ")               # trace_id, span_id
_OP = struct.Struct("<BQqHI")            # kind, cid, cseq, klen, vlen
_REP = struct.Struct("<BI")              # err, vlen
_EHDR = struct.Struct("<4sI")            # magic, mlen

MAX_OPS = 0xFFFF  # u16 nops; also the slot width of the native reply tag
MAX_KEY = 0xFFFF  # u16 klen
MAX_VALUE = 0xFFFFFFFF  # u32 vlen


class CapacityError(RPCError):
    """An op does not FIT the fe wire layout (key > u16, value > u32,
    batch > u16 ops).  Distinct from transport failure so a clerk can
    fall back to the pickled frame for that request instead of
    retrying/rotating — the op itself is fine, only the encoding is."""


def is_fe_frame(buf: bytes) -> bool:
    """True for any fe wire frame (request, reply, or error)."""
    return len(buf) >= 4 and buf[:2] == b"FE"


def encode_batch(ops, tc=None) -> bytes:
    """ops: iterable of (kind, key, value, cid, cseq[, tc]) wire tuples
    (per-op trailing tc elements are ignored — the fe frame's trace
    context is frame-scoped, pass it as `tc`)."""
    ops = tuple(ops)
    if len(ops) > MAX_OPS:
        raise CapacityError(f"fe_batch too wide: {len(ops)} > {MAX_OPS}")
    flags = FLAG_TRACE if tc is not None else 0
    out = bytearray(_HDR.pack(MAGIC_BATCH, flags, len(ops)))
    if tc is not None:
        out += _TC.pack(int(tc[0]) & (2**64 - 1), int(tc[1]) & (2**64 - 1))
    for t in ops:
        kind, key, value, cid, cseq = t[:5]
        kb = key.encode() if isinstance(key, str) else bytes(key)
        vb = value.encode() if isinstance(value, str) else bytes(value)
        if len(kb) > MAX_KEY or len(vb) > MAX_VALUE:
            raise CapacityError(
                f"op does not fit the fe wire (klen {len(kb)} > {MAX_KEY}"
                f" or vlen {len(vb)} > {MAX_VALUE})")
        out += _OP.pack(KIND_CODE[kind], int(cid) & (2**64 - 1), int(cseq),
                        len(kb), len(vb))
        out += kb
        out += vb
    return bytes(out)


def decode_batch(buf: bytes):
    """-> (ops, tc): ops is a tuple of (kind, key, value, cid, cseq)
    5-tuples (the classic fe_batch wire shape), tc the optional frame
    trace context.  This is the PYTHON decoder — the fallback servers'
    side of the schema; the native server never runs it."""
    if buf[:4] != MAGIC_BATCH:
        if buf[:3] == MAGIC_BATCH[:3]:
            raise RPCError(f"fe_batch version {buf[3]} != {VERSION}")
        raise RPCError("not an fe_batch frame")
    _, flags, nops = _HDR.unpack_from(buf, 0)
    off = _HDR.size
    tc = None
    if flags & FLAG_TRACE:
        tc = _TC.unpack_from(buf, off)
        off += _TC.size
    ops = []
    try:
        for _ in range(nops):
            kind, cid, cseq, klen, vlen = _OP.unpack_from(buf, off)
            off += _OP.size
            key = buf[off:off + klen].decode()
            off += klen
            value = buf[off:off + vlen].decode()
            off += vlen
            ops.append((KINDS[kind], key, value, cid, cseq))
    except (struct.error, IndexError, UnicodeDecodeError) as e:
        raise RPCError(f"malformed fe_batch frame: {e!r}") from e
    if off != len(buf):
        raise RPCError("trailing garbage in fe_batch frame")
    return tuple(ops), tc


def encode_replies(replies) -> bytes:
    """replies: iterable of (err, value) pairs (the kv reply shape).
    Non-enum errs or non-str values take the pickled escape hatch."""
    replies = tuple(replies)
    out = bytearray(_HDR.pack(MAGIC_REPLY, 0, len(replies)))
    for rep in replies:
        code = None
        if isinstance(rep, tuple) and len(rep) == 2 and \
                isinstance(rep[1], str):
            code = ERR_CODE.get(rep[0])
        if code is not None:
            vb = rep[1].encode()
        else:
            code = ERR_OTHER
            vb = pickle.dumps(rep, protocol=pickle.HIGHEST_PROTOCOL)
        out += _REP.pack(code, len(vb))
        out += vb
    return bytes(out)


def decode_replies(buf: bytes):
    """-> tuple of (err, value) reply pairs."""
    if buf[:4] != MAGIC_REPLY:
        raise RPCError("not an fe reply frame")
    _, _, nops = _HDR.unpack_from(buf, 0)
    off = _HDR.size
    reps = []
    try:
        for _ in range(nops):
            err, vlen = _REP.unpack_from(buf, off)
            off += _REP.size
            vb = buf[off:off + vlen]
            off += vlen
            if err == ERR_OTHER:
                reps.append(pickle.loads(vb))
            else:
                reps.append((ERRS[err], vb.decode()))
    except (struct.error, IndexError, pickle.UnpicklingError,
            UnicodeDecodeError) as e:
        raise RPCError(f"malformed fe reply frame: {e!r}") from e
    return tuple(reps)


def encode_error(msg: str) -> bytes:
    mb = msg.encode()
    return _EHDR.pack(MAGIC_ERROR, len(mb)) + mb


def decode_any_reply(buf: bytes):
    """Decode a reply-direction fe frame -> (ok, payload), the transport
    reply shape: (True, replies-tuple) or (False, message)."""
    if buf[:4] == MAGIC_REPLY:
        return True, decode_replies(buf)
    if buf[:4] == MAGIC_ERROR:
        _, mlen = _EHDR.unpack_from(buf, 0)
        return False, buf[_EHDR.size:_EHDR.size + mlen].decode(
            errors="replace")
    raise RPCError(f"unknown fe reply frame {buf[:4]!r}")
