"""fe wire — the versioned little-endian frame layout for the clerk
frontend's batched request path (ISSUE 11, ROADMAP item 1).

One schema, two decoders: this module is the PYTHON side (encoder for
clerks, decoder for the pure-Python fallback servers), and
`tpu6824/native/fewire.h` is the byte-for-byte C++ mirror the epoll loop
decodes with — straight into preallocated int64/int32 columnar buffers,
no GIL, no Python objects (the *Paxos Made Switch-y* dataplane bet with
our native server playing the P4 switch).  Any layout change bumps
``VERSION`` **in both files** and must keep the older decoder refusing
(not mis-parsing) the newer frame.

Frames ride the existing L0 transport framing (4-byte big-endian length
prefix) and are distinguished from the classic pickled tuples by magic:
pickle frames begin with ``\\x80`` (PROTO opcode), fe frames with
``FE``.  Old pickled ``fe_batch`` / ``get`` / ``put_append`` frames stay
first-class on every server — interop both directions is a contract,
not a transition state.

Layout v1 (all integers little-endian):

  request   'F' 'E' 'B' ver |u16 flags|u16 nops| [u64 tid,u64 sid]
            then nops records: u8 kind |u64 cid|i64 cseq|u16 klen|
            u32 vlen| key bytes | value bytes
  reply     'F' 'E' 'R' ver |u16 flags|u16 nops|
            then nops records: u8 err |u32 vlen| value bytes
  error     'F' 'E' 'E' ver |u32 mlen| utf-8 message
            (maps to RPCError at the client, like a (False, msg) reply)

flags bit 0 on a request: the optional tpuscope trace context
(trace_id, span_id) follows the header — the PR-5 third frame element,
frame-scoped.  kind and err are closed enums below; err 255 is the
escape hatch (value bytes carry a pickled (err, value) pair) so exotic
service replies survive the binary path without widening the enum.

Capability-gated v1 extensions (ISSUE 12, netfault): two further flag
bits add OPTIONAL header fields — bit 1 (`FLAG_DEADLINE`): a u32
remaining-op-budget in milliseconds follows the trace context, so the
server stops working on ops the clerk has already abandoned; bit 2
(`FLAG_CRC`): a u32 crc32 (zlib) of the whole frame EXCLUDING the crc
field itself follows, and reply frames echo the flag + their own crc.
A v1 decoder that predates these bits would MIS-parse a frame carrying
them, so a clerk only sets them when the endpoint's `fe_caps` probe
advertised `fe_deadline` / `fe_crc` — a frame with neither flag is
byte-identical to the original v1 layout, which is what keeps this a
compatible extension rather than a version bump.  The CRC is the
corruption DEFENSE the netfault injector exposes the need for: a byte
flip landing in the cid/cseq/key/value region of an otherwise
well-formed frame would silently alter an op (or poison the dup
filter); with the flag on, both decoders reject the frame as a
connection-scoped error instead — corruption never silently applies
and never demotes the wire format.
"""

from __future__ import annotations

import pickle
import struct
import zlib

from tpu6824.utils.errors import OK, ErrNoKey, ErrWrongGroup, RPCError

VERSION = 1

MAGIC_BATCH = b"FEB" + bytes([VERSION])
MAGIC_REPLY = b"FER" + bytes([VERSION])
MAGIC_ERROR = b"FEE" + bytes([VERSION])

FLAG_TRACE = 1     # request flags bit 0: (trace_id, span_id) present
FLAG_DEADLINE = 2  # bit 1: u32 op-budget ms present (caps-gated)
FLAG_CRC = 4       # bit 2: u32 frame crc32 present (caps-gated);
#                    replies echo the flag + carry their own crc

# Closed op-kind enum — the int32 the native decoder writes into the
# kind column.  Order is part of the schema.  Codes 3-6 are the
# caps-gated TXN EXTENSION (ISSUE 13): 2PC phase ops whose value field
# carries a JSON payload (utf-8, so the existing value bytes layout is
# untouched).  A clerk only sends them to an endpoint whose `fe_caps`
# advertised `fe_txn` — an old Python decoder's KINDS lookup would
# refuse them as malformed, and the C++ ingest decoder REFUSES them by
# design (fewire.h keeps kNumKinds at 3: an ingest server cannot serve
# 2PC, so its caps never advertise fe_txn and a stray txn frame is a
# counted connection-scoped reject, never a mis-parse).
KINDS = ("get", "put", "append",
         "txn_prepare", "txn_commit", "txn_abort", "txn_coord")
TXN_KINDS = frozenset(KINDS[3:])
KIND_CODE = {k: i for i, k in enumerate(KINDS)}

# Closed reply-err enum; 255 = pickled escape hatch.
ERRS = (OK, ErrNoKey, ErrWrongGroup)
ERR_CODE = {e: i for i, e in enumerate(ERRS)}
ERR_OTHER = 255

_HDR = struct.Struct("<4sHH")            # magic, flags, nops
_TC = struct.Struct("<QQ")               # trace_id, span_id
_U32 = struct.Struct("<I")               # deadline_ms / crc32 fields
_OP = struct.Struct("<BQqHI")            # kind, cid, cseq, klen, vlen
_REP = struct.Struct("<BI")              # err, vlen
_EHDR = struct.Struct("<4sI")            # magic, mlen

MAX_OPS = 0xFFFF  # u16 nops; also the slot width of the native reply tag
MAX_KEY = 0xFFFF  # u16 klen
MAX_VALUE = 0xFFFFFFFF  # u32 vlen


class CapacityError(RPCError):
    """An op does not FIT the fe wire layout (key > u16, value > u32,
    batch > u16 ops).  Distinct from transport failure so a clerk can
    fall back to the pickled frame for that request instead of
    retrying/rotating — the op itself is fine, only the encoding is."""


def is_fe_frame(buf: bytes) -> bool:
    """True for any fe wire frame (request, reply, or error)."""
    return len(buf) >= 4 and buf[:2] == b"FE"


def _seal_crc(out: bytearray, crc_off: int) -> bytes:
    """Stamp the frame's crc32 into the 4 reserved bytes at `crc_off`
    (computed over every OTHER byte of the frame)."""
    c = zlib.crc32(out[:crc_off])
    c = zlib.crc32(out[crc_off + 4:], c)
    out[crc_off:crc_off + 4] = _U32.pack(c & 0xFFFFFFFF)
    return bytes(out)


def _check_crc(buf: bytes, crc_off: int) -> bool:
    (want,) = _U32.unpack_from(buf, crc_off)
    c = zlib.crc32(buf[:crc_off])
    c = zlib.crc32(buf[crc_off + 4:], c)
    return (c & 0xFFFFFFFF) == want


def encode_batch(ops, tc=None, deadline_ms=None, crc=False) -> bytes:
    """ops: iterable of (kind, key, value, cid, cseq[, tc]) wire tuples
    (per-op trailing tc elements are ignored — the fe frame's trace
    context is frame-scoped, pass it as `tc`).  `deadline_ms` / `crc`
    add the caps-gated v1 extension fields — only pass them for an
    endpoint whose fe_caps advertised `fe_deadline` / `fe_crc` (an old
    decoder would mis-parse the extended header)."""
    ops = tuple(ops)
    if len(ops) > MAX_OPS:
        raise CapacityError(f"fe_batch too wide: {len(ops)} > {MAX_OPS}")
    flags = FLAG_TRACE if tc is not None else 0
    if deadline_ms is not None:
        flags |= FLAG_DEADLINE
    if crc:
        flags |= FLAG_CRC
    out = bytearray(_HDR.pack(MAGIC_BATCH, flags, len(ops)))
    if tc is not None:
        out += _TC.pack(int(tc[0]) & (2**64 - 1), int(tc[1]) & (2**64 - 1))
    if deadline_ms is not None:
        out += _U32.pack(max(0, min(int(deadline_ms), 0xFFFFFFFF)))
    crc_off = None
    if crc:
        crc_off = len(out)
        out += b"\x00\x00\x00\x00"
    for t in ops:
        kind, key, value, cid, cseq = t[:5]
        kb = key.encode() if isinstance(key, str) else bytes(key)
        vb = value.encode() if isinstance(value, str) else bytes(value)
        if len(kb) > MAX_KEY or len(vb) > MAX_VALUE:
            raise CapacityError(
                f"op does not fit the fe wire (klen {len(kb)} > {MAX_KEY}"
                f" or vlen {len(vb)} > {MAX_VALUE})")
        out += _OP.pack(KIND_CODE[kind], int(cid) & (2**64 - 1), int(cseq),
                        len(kb), len(vb))
        out += kb
        out += vb
    if crc_off is not None:
        return _seal_crc(out, crc_off)
    return bytes(out)


def decode_batch(buf: bytes):
    """-> (ops, tc): ops is a tuple of (kind, key, value, cid, cseq)
    5-tuples (the classic fe_batch wire shape), tc the optional frame
    trace context.  This is the PYTHON decoder — the fallback servers'
    side of the schema; the native server never runs it."""
    ops, tc, _meta = decode_batch_meta(buf)
    return ops, tc


def decode_batch_meta(buf: bytes):
    """-> (ops, tc, meta) with meta = {"deadline_ms": int|None, "crc":
    bool} — the server-side decoder: verifies the frame CRC when
    present (mismatch is a malformed frame — a connection-scoped
    reject, never a crash or a mis-applied op) and surfaces the
    propagated op budget."""
    if buf[:4] != MAGIC_BATCH:
        if buf[:3] == MAGIC_BATCH[:3]:
            raise RPCError(f"fe_batch version {buf[3]} != {VERSION}")
        raise RPCError("not an fe_batch frame")
    _, flags, nops = _HDR.unpack_from(buf, 0)
    off = _HDR.size
    tc = None
    deadline_ms = None
    has_crc = bool(flags & FLAG_CRC)
    try:
        if flags & FLAG_TRACE:
            tc = _TC.unpack_from(buf, off)
            off += _TC.size
        if flags & FLAG_DEADLINE:
            (deadline_ms,) = _U32.unpack_from(buf, off)
            off += _U32.size
        if has_crc:
            if len(buf) < off + 4 or not _check_crc(buf, off):
                raise RPCError("fe_batch frame CRC mismatch")
            off += _U32.size
        ops = []
        for _ in range(nops):
            kind, cid, cseq, klen, vlen = _OP.unpack_from(buf, off)
            off += _OP.size
            key = buf[off:off + klen].decode()
            off += klen
            value = buf[off:off + vlen].decode()
            off += vlen
            ops.append((KINDS[kind], key, value, cid, cseq))
    except (struct.error, IndexError, UnicodeDecodeError) as e:
        raise RPCError(f"malformed fe_batch frame: {e!r}") from e
    if off != len(buf):
        raise RPCError("trailing garbage in fe_batch frame")
    return tuple(ops), tc, {"deadline_ms": deadline_ms, "crc": has_crc}


def encode_replies(replies, crc=False) -> bytes:
    """replies: iterable of (err, value) pairs (the kv reply shape).
    Non-enum errs or non-str values take the pickled escape hatch.
    `crc=True` (echoing a request's FLAG_CRC) stamps the reply with
    its own crc32 so reply-direction corruption is detectable too."""
    replies = tuple(replies)
    out = bytearray(_HDR.pack(MAGIC_REPLY, FLAG_CRC if crc else 0,
                              len(replies)))
    crc_off = None
    if crc:
        crc_off = len(out)
        out += b"\x00\x00\x00\x00"
    for rep in replies:
        code = None
        if isinstance(rep, tuple) and len(rep) == 2 and \
                isinstance(rep[1], str):
            code = ERR_CODE.get(rep[0])
        if code is not None:
            vb = rep[1].encode()
        else:
            code = ERR_OTHER
            vb = pickle.dumps(rep, protocol=pickle.HIGHEST_PROTOCOL)
        out += _REP.pack(code, len(vb))
        out += vb
    if crc_off is not None:
        return _seal_crc(out, crc_off)
    return bytes(out)


def decode_replies(buf: bytes):
    """-> tuple of (err, value) reply pairs.  A reply carrying FLAG_CRC
    is verified; a mismatch raises (the clerk tears the connection and
    retries — at-most-once rests on the dup filter, as for any torn
    reply)."""
    if buf[:4] != MAGIC_REPLY:
        raise RPCError("not an fe reply frame")
    _, flags, nops = _HDR.unpack_from(buf, 0)
    off = _HDR.size
    reps = []
    try:
        if flags & FLAG_CRC:
            if len(buf) < off + 4 or not _check_crc(buf, off):
                raise RPCError("fe reply frame CRC mismatch")
            off += _U32.size
        for _ in range(nops):
            err, vlen = _REP.unpack_from(buf, off)
            off += _REP.size
            vb = buf[off:off + vlen]
            off += vlen
            if err == ERR_OTHER:
                reps.append(pickle.loads(vb))
            else:
                reps.append((ERRS[err], vb.decode()))
    except (struct.error, IndexError, pickle.UnpicklingError,
            UnicodeDecodeError) as e:
        raise RPCError(f"malformed fe reply frame: {e!r}") from e
    if off != len(buf):
        # Exact-length discipline doubles as corruption armor: a flip
        # that clears FLAG_CRC leaves the 4 crc bytes stranded in the
        # record region, so the parse cannot land on the frame end.
        raise RPCError("trailing garbage in fe reply frame")
    return tuple(reps)


def encode_error(msg: str) -> bytes:
    mb = msg.encode()
    return _EHDR.pack(MAGIC_ERROR, len(mb)) + mb


def decode_any_reply(buf: bytes):
    """Decode a reply-direction fe frame -> (ok, payload), the transport
    reply shape: (True, replies-tuple) or (False, message)."""
    if buf[:4] == MAGIC_REPLY:
        return True, decode_replies(buf)
    if buf[:4] == MAGIC_ERROR:
        _, mlen = _EHDR.unpack_from(buf, 0)
        return False, buf[_EHDR.size:_EHDR.size + mlen].decode(
            errors="replace")
    raise RPCError(f"unknown fe reply frame {buf[:4]!r}")
