"""Native L0 transport server — the C++ epoll event loop behind the same
surface as `transport.Server`.

The reference's runtime is its per-server accept loop (`paxos/paxos.go:
524-552`); `tpu6824/native/rpcserver.cpp` is that loop as a native epoll
event loop (fault injection, rpc counting, framing all in C++), while the
codec and handlers stay in Python: the loop hands each request payload to a
callback, a handler thread computes the reply, and the reply re-enters the
loop through an eventfd — so slow handlers never stall accepts and many
connections are multiplexed without a thread per socket.

Drop-in: `NativeServer` exposes the `transport.Server` API and contract —
register → start → serve; kill() is final but rpc_count/set_unreliable/
deafen stay safe to call afterwards; one handler thread per in-flight
request (the Python loop's thread-per-connection semantics); unseeded
servers get independent OS-entropy fault streams.  It speaks the same wire
format, so `transport.call`, `Proxy`, the harness's partition/alias tricks,
and the DelayProxy all work unchanged against it.  Falls back to
`transport.Server` when no C++ toolchain is available (`native_available()`
/ `make_server`)."""

from __future__ import annotations

import ctypes
import os
import pickle
import threading

from tpu6824.native.build import load
from tpu6824.obs import tracing as _tracing
from tpu6824.rpc import transport
from tpu6824.utils.errors import RPCError
from tpu6824.utils import crashsink

_SRC = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                    "native", "rpcserver.cpp")

_CB = ctypes.CFUNCTYPE(None, ctypes.c_uint64,
                       ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64)

_lib = None
_lib_once = threading.Lock()


def _get_lib():
    global _lib
    with _lib_once:
        if _lib is None:
            lib = load("rpcserver.so", _SRC)
            if lib is not None:
                lib.rpcsrv_start.restype = ctypes.c_void_p
                lib.rpcsrv_start.argtypes = [ctypes.c_char_p,
                                             ctypes.c_uint64, _CB]
                lib.rpcsrv_reply.argtypes = [
                    ctypes.c_void_p, ctypes.c_uint64,
                    ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64,
                ]
                lib.rpcsrv_set_unreliable.argtypes = [ctypes.c_void_p,
                                                      ctypes.c_int]
                lib.rpcsrv_rpc_count.restype = ctypes.c_int64
                lib.rpcsrv_rpc_count.argtypes = [ctypes.c_void_p]
                lib.rpcsrv_deafen.argtypes = [ctypes.c_void_p]
                lib.rpcsrv_kill.argtypes = [ctypes.c_void_p]
                lib.rpcsrv_free.argtypes = [ctypes.c_void_p]
            _lib = lib or False
    return _lib or None


def native_available() -> bool:
    return _get_lib() is not None


class NativeServer:
    """transport.Server's surface, backed by the C++ event loop.  The
    socket binds in `start()` (register handlers first, then expose — the
    reference order, so a dialer never sees a live socket with no
    handlers)."""

    def __init__(self, addr: str, seed: int | None = None):
        lib = _get_lib()
        if lib is None:
            raise RPCError("native transport unavailable (no C++ toolchain)")
        self.addr = addr
        os.makedirs(os.path.dirname(addr) or ".", exist_ok=True)
        self._lib = lib
        self._handlers: dict[str, callable] = {}
        # Event-loop handlers (register_inline): run ON the C++ epoll
        # callback thread, no per-request handler thread, reply deferred
        # via send_reply() from any thread — the clerk-frontend seam.
        self._inline: dict[str, callable] = {}
        self._lock = threading.Lock()  # serializes reply vs kill/free
        self._dead = False
        self._srv = None
        self._final_rpc_count = 0
        self._unreliable = False
        # Unseeded servers must have INDEPENDENT fault streams (the Python
        # loop uses Random(None) per server); xorshift state must be nonzero.
        s = seed if seed is not None else int.from_bytes(os.urandom(8), "little")
        self._seed = (s & (2**64 - 1)) or 1
        # The CFUNCTYPE object must outlive the server (C holds the pointer).
        self._cb = _CB(self._on_request)

    # ------------------------------------------------------------ surface

    def register(self, name: str, fn) -> "NativeServer":
        self._handlers[name] = fn
        return self

    def register_obj(self, obj, methods: list[str] | None = None) -> "NativeServer":
        for m in transport.exported_methods(obj, methods):
            self._handlers[m] = getattr(obj, m)
        return self

    def register_inline(self, name: str, fn) -> "NativeServer":
        """Register an EVENT-LOOP handler: `fn(conn_id, args, wctx)` runs
        inline on the C++ epoll callback thread — no per-request handler
        thread is spawned, so a frontend multiplexing thousands of
        connections costs zero threads per request.  The contract is the
        event-loop discipline (tpusan `blocking-in-eventloop`): the
        handler must only decode/enqueue/wake — never sleep, wait on a
        lock, or make a blocking call — and it does NOT return a reply;
        it (or any other thread) answers later via `send_reply(conn_id,
        obj)` / `send_close(conn_id)`.  A handler that raises drops the
        connection (close marker), like an undecodable frame."""
        self._inline[name] = fn
        return self

    def send_reply(self, conn_id: int, obj) -> None:
        """Deferred ok-reply for an inline-handled request: pickles
        `(True, obj)` and hands it to the epoll loop (eventfd wake) —
        callable from any thread, non-blocking."""
        try:
            raw = pickle.dumps((True, obj), protocol=pickle.HIGHEST_PROTOCOL)
        except Exception as e:  # noqa: BLE001 — degrade like _serve does
            raw = pickle.dumps(
                (False, f"unserializable reply ({e!r:.100})"),
                protocol=pickle.HIGHEST_PROTOCOL)
        self._send_reply(conn_id, raw)

    def send_error(self, conn_id: int, msg: str) -> None:
        """Deferred app-level error reply ((False, msg) — the caller's
        transport.call raises RPCError(msg))."""
        self._send_reply(conn_id, pickle.dumps(
            (False, msg), protocol=pickle.HIGHEST_PROTOCOL))

    def send_close(self, conn_id: int) -> None:
        """Drop the connection without replying (the RPCError-refusal
        path of the threaded handlers)."""
        self._send_reply(conn_id, b"")

    def start(self) -> "NativeServer":
        with self._lock:
            if self._dead or self._srv is not None:
                return self
            self._srv = self._lib.rpcsrv_start(self.addr.encode(),
                                               self._seed, self._cb)
            if not self._srv:
                raise RPCError(f"native transport failed to bind {self.addr}")
            if self._unreliable:  # flag set before start
                self._lib.rpcsrv_set_unreliable(self._srv, 1)
        return self

    def set_unreliable(self, flag: bool) -> None:
        with self._lock:
            self._unreliable = bool(flag)
            if self._srv is not None and not self._dead:
                self._lib.rpcsrv_set_unreliable(self._srv, 1 if flag else 0)

    @property
    def rpc_count(self) -> int:
        with self._lock:
            if self._srv is not None and not self._dead:
                return int(self._lib.rpcsrv_rpc_count(self._srv))
            return self._final_rpc_count  # post-kill reads stay valid

    def deafen(self) -> None:
        """Reversible deafness, same contract as transport.Server: the
        socket path is renamed aside in Python (the C++ loop keeps its
        bound inode and never touches the path again), so undeafen() can
        restore it.  The lib's rpcsrv_deafen (one-way unlink) is no
        longer used — rename gives identical dial-failure semantics."""
        with self._lock:
            if self._srv is not None and not self._dead:
                try:
                    os.rename(self.addr, self.addr + ".deaf")
                except FileNotFoundError:
                    pass

    def undeafen(self) -> None:
        with self._lock:
            try:
                os.rename(self.addr + ".deaf", self.addr)
            except FileNotFoundError:
                pass

    def kill(self) -> None:
        with self._lock:
            if self._dead:
                return
            self._dead = True
            if self._srv is not None:
                self._final_rpc_count = int(
                    self._lib.rpcsrv_rpc_count(self._srv))
                self._lib.rpcsrv_kill(self._srv)
                try:  # a deafened server's bound inode lives at .deaf
                    os.unlink(self.addr + ".deaf")
                except FileNotFoundError:
                    pass
                # kill joined the loop → no new callbacks; the lock ensures
                # no in-flight _send_reply still holds the old pointer.
                self._lib.rpcsrv_free(self._srv)
                self._srv = None

    # ------------------------------------------------------------ plumbing

    def _on_request(self, conn_id: int, data, length: int) -> None:
        # Runs on the C++ loop thread (ctypes grabs the GIL): copy out and
        # hand off so the loop returns to epoll immediately.  One thread per
        # in-flight request — the Python accept loop's semantics, so N
        # concurrently blocking handlers never starve request N+1.
        # With inline handlers registered, the frame is decoded HERE and an
        # inline rpc is served on this thread (decode + enqueue + wake; the
        # event-loop discipline) — zero handler threads on the batched path.
        payload = ctypes.string_at(data, length)
        frame = None
        if self._inline:
            try:
                frame = pickle.loads(payload)
                fn = self._inline.get(frame[0])
            except Exception:  # undecodable frame: drop (cf. _serve)
                self._send_reply(conn_id, b"")
                return
            if fn is not None:
                try:
                    fn(conn_id, frame[1],
                       frame[2] if len(frame) > 2 else None)
                except Exception as e:  # noqa: BLE001 — loop must survive
                    crashsink.record("native-rpc-inline", e, fatal=False)
                    self._send_reply(conn_id, b"")
                return
            # Non-inline rpc on a mixed server: hand the ALREADY-decoded
            # frame to the worker (never decode twice).
        threading.Thread(
            target=crashsink.guarded(self._serve, "native-rpc-serve"),
            args=(conn_id, payload, frame), daemon=True).start()

    def _serve(self, conn_id: int, payload: bytes, frame=None) -> None:
        try:
            if frame is None:
                frame = pickle.loads(payload)
            # Optional third element: a tpuscope TraceContext from a
            # tracing-enabled peer (transport.call's envelope; untagged
            # 2-tuples are the common wire).
            rpcname, args = frame[0], frame[1]
            wctx = frame[2] if len(frame) > 2 else None
            fn = self._handlers.get(rpcname)
            if fn is None:
                reply = (False, f"no such rpc: {rpcname}")
            else:
                try:
                    if wctx is not None:
                        with _tracing.use_ctx(_tracing.TraceContext(*wctx)):
                            reply = (True, fn(*args))
                    else:
                        reply = (True, fn(*args))
                except RPCError:
                    # Drop the connection without replying, as
                    # transport.Server does (zero-length = close marker).
                    self._send_reply(conn_id, b"")
                    return
                except Exception as e:
                    reply = (False, e)
        # tpusan: ok(daemon-bare-except) — undecodable frame is a
        # protocol-level drop answered with the close marker, not a
        # thread death; the client sees the dead connection and retries.
        except Exception:
            self._send_reply(conn_id, b"")  # undecodable frame: drop
            return
        try:
            raw = pickle.dumps(reply, protocol=pickle.HIGHEST_PROTOCOL)
        except Exception as e:
            raw = pickle.dumps(
                (False, f"unserializable reply ({e!r:.100})"),
                protocol=pickle.HIGHEST_PROTOCOL)
        self._send_reply(conn_id, raw)

    def _send_reply(self, conn_id: int, raw: bytes) -> None:
        buf = (ctypes.c_uint8 * len(raw)).from_buffer_copy(raw)
        with self._lock:
            if self._dead or self._srv is None:
                return
            self._lib.rpcsrv_reply(self._srv, conn_id, buf, len(raw))


def make_server(addr: str, seed: int | None = None, prefer_native=True):
    """Native event-loop server when the toolchain allows, else the Python
    accept-loop server — same surface either way.  NOT yet started: register
    handlers, then call .start() (register-before-expose, so a dialer never
    reaches a socket with no handlers behind it)."""
    if prefer_native and native_available():
        return NativeServer(addr, seed=seed)
    return transport.Server(addr, seed=seed)
