"""Native L0 transport server — the C++ epoll event loop behind the same
surface as `transport.Server`.

The reference's runtime is its per-server accept loop (`paxos/paxos.go:
524-552`); `tpu6824/native/rpcserver.cpp` is that loop as a native epoll
event loop (fault injection, rpc counting, framing all in C++), while the
codec and handlers stay in Python: the loop hands each request payload to a
callback, a handler thread computes the reply, and the reply re-enters the
loop through an eventfd — so slow handlers never stall accepts and many
connections are multiplexed without a thread per socket.

Drop-in: `NativeServer` exposes the `transport.Server` API and contract —
register → start → serve; kill() is final but rpc_count/set_unreliable/
deafen stay safe to call afterwards; one handler thread per in-flight
request (the Python loop's thread-per-connection semantics); unseeded
servers get independent OS-entropy fault streams.  It speaks the same wire
format, so `transport.call`, `Proxy`, the harness's partition/alias tricks,
and the DelayProxy all work unchanged against it.  Falls back to
`transport.Server` when no C++ toolchain is available (`native_available()`
/ `make_server`)."""

from __future__ import annotations

import ctypes
import os
import pickle
import threading

from tpu6824.native.build import load
from tpu6824.obs import tracing as _tracing
from tpu6824.rpc import transport, wire
from tpu6824.utils.errors import RPCError
from tpu6824.utils import crashsink

_SRC = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                    "native", "rpcserver.cpp")

_CB = ctypes.CFUNCTYPE(None, ctypes.c_uint64,
                       ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64)

_lib = None
_lib_once = threading.Lock()


def _get_lib():
    global _lib
    with _lib_once:
        if _lib is None:
            # TPU6824_NATIVE_SANITIZE=thread loads the parallel
            # -fsanitize=thread artifact (the TSAN soak's seam); the
            # child process must also LD_PRELOAD libtsan — see
            # tests/test_native_tsan.py for the full recipe.
            lib = load("rpcserver.so", _SRC,
                       sanitize=os.environ.get("TPU6824_NATIVE_SANITIZE")
                       or None)
            if lib is not None:
                lib.rpcsrv_start.restype = ctypes.c_void_p
                lib.rpcsrv_start.argtypes = [ctypes.c_char_p,
                                             ctypes.c_uint64, _CB]
                lib.rpcsrv_reply.argtypes = [
                    ctypes.c_void_p, ctypes.c_uint64,
                    ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64,
                ]
                lib.rpcsrv_set_unreliable.argtypes = [ctypes.c_void_p,
                                                      ctypes.c_int]
                lib.rpcsrv_rpc_count.restype = ctypes.c_int64
                lib.rpcsrv_rpc_count.argtypes = [ctypes.c_void_p]
                lib.rpcsrv_deafen.argtypes = [ctypes.c_void_p]
                lib.rpcsrv_kill.argtypes = [ctypes.c_void_p]
                lib.rpcsrv_free.argtypes = [ctypes.c_void_p]
                # Native-ingest surface (ISSUE 11).  An older checked-in
                # .so lacks these; the hash-staleness rebuild in build.py
                # makes that unreachable in practice, but probe anyway so
                # a failed rebuild degrades to the Python inline path.
                if hasattr(lib, "rpcsrv_ingest_enable"):
                    # Array arguments travel as RAW addresses (c_void_p
                    # ints from numpy's .ctypes.data): a typed
                    # data_as()/cast() per call builds a ctypes
                    # reference CYCLE (pointer ↔ _objects dict) that
                    # only gc can reclaim — measured at ~3 objects per
                    # call by the zero-alloc probe.  The caller keeps
                    # the arrays alive across the call.
                    vp = ctypes.c_void_p
                    lib.rpcsrv_ingest_enable.restype = ctypes.c_int
                    lib.rpcsrv_ingest_enable.argtypes = [
                        vp, ctypes.c_int64]
                    lib.rpcsrv_ingest_poll1.restype = ctypes.c_int64
                    lib.rpcsrv_ingest_poll1.argtypes = [
                        vp, vp, vp, vp, vp, vp, vp, ctypes.c_int64]
                    lib.rpcsrv_ingest_val_intern.restype = ctypes.c_int32
                    lib.rpcsrv_ingest_val_intern.argtypes = [
                        vp, ctypes.c_char_p, ctypes.c_int64]
                    lib.rpcsrv_ingest_val_intern_many.argtypes = [
                        vp, ctypes.c_char_p, vp, vp, vp,
                        ctypes.c_int64]
                    lib.rpcsrv_ingest_push.argtypes = [
                        vp, vp, vp, vp, ctypes.c_int64]
                    lib.rpcsrv_ingest_pending.restype = ctypes.c_int64
                    lib.rpcsrv_ingest_pending.argtypes = [
                        vp, ctypes.c_uint64, vp]
                    lib.rpcsrv_ingest_fail.argtypes = [
                        vp, ctypes.c_uint64, ctypes.c_char_p]
                    lib.rpcsrv_ingest_reap.restype = ctypes.c_int64
                    lib.rpcsrv_ingest_reap.argtypes = [
                        vp, vp, ctypes.c_int64]
                    lib.rpcsrv_ingest_get.restype = ctypes.c_int64
                    lib.rpcsrv_ingest_get.argtypes = [
                        vp, ctypes.c_int, ctypes.c_int32,
                        ctypes.c_char_p, ctypes.c_int64]
                    lib.rpcsrv_ingest_decref.restype = ctypes.c_int64
                    lib.rpcsrv_ingest_decref.argtypes = [
                        vp, ctypes.c_int, vp, ctypes.c_int64, vp]
                    lib.rpcsrv_ingest_stats.argtypes = [vp, vp]
                # opscope flush-stage histogram (ISSUE 15) — probed like
                # the rest of the extended surface: a stale .so simply
                # reports no flush stage rather than crashing.
                if hasattr(lib, "rpcsrv_opscope_flush"):
                    lib.rpcsrv_opscope_flush.argtypes = [
                        ctypes.c_void_p, ctypes.c_void_p]
                # netfault reply-path hook + decode-reject counter
                # (ISSUE 12).  Probed like the ingest surface: absent
                # on a stale .so, in which case injection/counting
                # degrade to unavailable rather than crashing.
                if hasattr(lib, "rpcsrv_netfault_arm"):
                    lib.rpcsrv_netfault_arm.argtypes = [
                        ctypes.c_void_p, ctypes.c_int, ctypes.c_double]
                    lib.rpcsrv_netfault_plan.argtypes = [
                        ctypes.c_void_p, ctypes.c_uint64,
                        ctypes.POINTER(ctypes.c_double)]
                    lib.rpcsrv_netfault_clear.argtypes = [ctypes.c_void_p]
                    lib.rpcsrv_netfault_injected.restype = ctypes.c_int64
                    lib.rpcsrv_netfault_injected.argtypes = [
                        ctypes.c_void_p]
                    lib.rpcsrv_wire_rejected.restype = ctypes.c_int64
                    lib.rpcsrv_wire_rejected.argtypes = [ctypes.c_void_p]
                    lib.rpcsrv_set_io_deadline_ms.argtypes = [
                        ctypes.c_void_p, ctypes.c_int64]
            _lib = lib or False
    return _lib or None


def native_available() -> bool:
    return _get_lib() is not None


class NativeServer:
    """transport.Server's surface, backed by the C++ event loop.  The
    socket binds in `start()` (register handlers first, then expose — the
    reference order, so a dialer never sees a live socket with no
    handlers)."""

    def __init__(self, addr: str, seed: int | None = None):
        lib = _get_lib()
        if lib is None:
            raise RPCError("native transport unavailable (no C++ toolchain)")
        self.addr = addr
        os.makedirs(os.path.dirname(addr) or ".", exist_ok=True)
        self._lib = lib
        self._handlers: dict[str, callable] = {}
        # Python-side handler for VERSIONED fe wire frames (rpc/wire.py)
        # when C++ ingest is off: decoded here, answered natively.
        self._native_batch = None
        self._ingest_fd: int | None = None
        # Event-loop handlers (register_inline): run ON the C++ epoll
        # callback thread, no per-request handler thread, reply deferred
        # via send_reply() from any thread — the clerk-frontend seam.
        self._inline: dict[str, callable] = {}
        self._lock = threading.Lock()  # serializes reply vs kill/free
        self._dead = False
        self._srv = None
        self._final_rpc_count = 0
        self._unreliable = False
        # Unseeded servers must have INDEPENDENT fault streams (the Python
        # loop uses Random(None) per server); xorshift state must be nonzero.
        s = seed if seed is not None else int.from_bytes(os.urandom(8), "little")
        self._seed = (s & (2**64 - 1)) or 1
        # The CFUNCTYPE object must outlive the server (C holds the pointer).
        self._cb = _CB(self._on_request)

    # ------------------------------------------------------------ surface

    def register(self, name: str, fn) -> "NativeServer":
        self._handlers[name] = fn
        return self

    def register_obj(self, obj, methods: list[str] | None = None) -> "NativeServer":
        for m in transport.exported_methods(obj, methods):
            self._handlers[m] = getattr(obj, m)
        return self

    def register_inline(self, name: str, fn) -> "NativeServer":
        """Register an EVENT-LOOP handler: `fn(conn_id, args, wctx)` runs
        inline on the C++ epoll callback thread — no per-request handler
        thread is spawned, so a frontend multiplexing thousands of
        connections costs zero threads per request.  The contract is the
        event-loop discipline (tpusan `blocking-in-eventloop`): the
        handler must only decode/enqueue/wake — never sleep, wait on a
        lock, or make a blocking call — and it does NOT return a reply;
        it (or any other thread) answers later via `send_reply(conn_id,
        obj)` / `send_close(conn_id)`.  A handler that raises drops the
        connection (close marker), like an undecodable frame."""
        self._inline[name] = fn
        return self

    def register_native_batch(self, fn) -> "NativeServer":
        """Event-loop handler for fe wire frames that reach PYTHON (C++
        ingest off — custom op factories, or a lib without the ingest
        surface): `fn(conn_id, ops, tc, meta)` with the frame already
        decoded by rpc/wire.py (meta = the decode_batch_meta dict:
        propagated deadline + crc echo).  Same discipline as
        register_inline; replies go out via send_reply_native/
        send_error_native."""
        self._native_batch = fn
        return self

    # ------------------------------------------------- netfault surface
    # Reply-path byte-fault injection (ISSUE 12): the C++-side hook that
    # makes native-ingest connections injectable (their request path
    # never re-enters Python).  Uniform arm/disarm shape with
    # netfault.WireFault so the nemesis NetTarget drives both.

    def netfault_arm(self, kind: str, frac: float = 0.5) -> None:
        from tpu6824.rpc.netfault import NET_FAULT_KINDS

        with self._lock:
            if self._srv is not None and not self._dead and \
                    hasattr(self._lib, "rpcsrv_netfault_arm"):
                self._lib.rpcsrv_netfault_arm(
                    self._srv, NET_FAULT_KINDS.index(kind), float(frac))

    def netfault_plan(self, seed: int, rates: dict) -> None:
        from tpu6824.rpc.netfault import NET_FAULT_KINDS

        arr = (ctypes.c_double * len(NET_FAULT_KINDS))(
            *[float(rates.get(k, 0.0)) for k in NET_FAULT_KINDS])
        with self._lock:
            if self._srv is not None and not self._dead and \
                    hasattr(self._lib, "rpcsrv_netfault_plan"):
                self._lib.rpcsrv_netfault_plan(self._srv, seed, arr)

    def netfault_clear(self) -> None:
        with self._lock:
            if self._srv is not None and not self._dead and \
                    hasattr(self._lib, "rpcsrv_netfault_clear"):
                self._lib.rpcsrv_netfault_clear(self._srv)

    @property
    def netfault_injected(self) -> int:
        with self._lock:
            if self._srv is not None and not self._dead and \
                    hasattr(self._lib, "rpcsrv_netfault_injected"):
                return int(self._lib.rpcsrv_netfault_injected(self._srv))
        return 0

    @property
    def wire_rejected(self) -> int:
        """Malformed/oversized frames the C++ decode state machine
        rejected (connection-scoped) — the Python-side rejects are
        counted straight into rpc.wire.rejected as they happen."""
        with self._lock:
            if self._srv is not None and not self._dead and \
                    hasattr(self._lib, "rpcsrv_wire_rejected"):
                return int(self._lib.rpcsrv_wire_rejected(self._srv))
        return 0

    def set_io_deadline(self, seconds: float) -> None:
        """Per-conn I/O-phase deadline (slow-loris bound): a conn that
        cannot finish a frame read or a reply write within this window
        is closed.  Default 30s (the transport contract)."""
        with self._lock:
            if self._srv is not None and not self._dead and \
                    hasattr(self._lib, "rpcsrv_set_io_deadline_ms"):
                self._lib.rpcsrv_set_io_deadline_ms(
                    self._srv, int(seconds * 1000))

    def enable_ingest(self, max_ops: int = 1 << 16) -> "NativeIngest | None":
        """Turn on zero-GIL ingest (call right AFTER start(), before
        traffic — the C handle must exist; a frame racing the enable
        just takes the Python decode path once): fe wire frames decode
        on the C++ loop thread into columnar buffers, and the reply
        ring serializes responses without re-entering Python.  Returns
        the NativeIngest handle (poll/push/reap surface), or None when
        the loaded lib predates the ingest ABI."""
        if not hasattr(self._lib, "rpcsrv_ingest_enable"):
            return None
        with self._lock:
            if self._dead:
                return None
            if self._srv is None:
                raise RPCError("enable_ingest must run after start()")
            fd = self._lib.rpcsrv_ingest_enable(self._srv, max_ops)
            if fd < 0:
                return None
            self._ingest_fd = fd
            return NativeIngest(self)

    def send_reply(self, conn_id: int, obj) -> None:
        """Deferred ok-reply for an inline-handled request: pickles
        `(True, obj)` and hands it to the epoll loop (eventfd wake) —
        callable from any thread, non-blocking."""
        try:
            raw = pickle.dumps((True, obj), protocol=pickle.HIGHEST_PROTOCOL)
        except Exception as e:  # noqa: BLE001 — degrade like _serve does
            raw = pickle.dumps(
                (False, f"unserializable reply ({e!r:.100})"),
                protocol=pickle.HIGHEST_PROTOCOL)
        self._send_reply(conn_id, raw)

    def send_error(self, conn_id: int, msg: str) -> None:
        """Deferred app-level error reply ((False, msg) — the caller's
        transport.call raises RPCError(msg))."""
        self._send_reply(conn_id, pickle.dumps(
            (False, msg), protocol=pickle.HIGHEST_PROTOCOL))

    def send_close(self, conn_id: int) -> None:
        """Drop the connection without replying (the RPCError-refusal
        path of the threaded handlers)."""
        self._send_reply(conn_id, b"")

    def send_reply_native(self, conn_id: int, replies,
                          crc: bool = False) -> None:
        """Deferred reply to an fe wire frame: FER-encoded (err, value)
        pairs — the versioned-layout twin of send_reply.  `crc` echoes
        a request's FLAG_CRC.  An encoded reply past the transport
        frame cap answers with an explicit fe error instead (parity
        with the C++ reply ring and transport.Server — a silently
        oversized frame the client cap rejects is a retry livelock)."""
        raw = wire.encode_replies(replies, crc=crc)
        if len(raw) > transport._MAX_FRAME:
            raw = wire.encode_error("reply too large for one fe frame")
        self._send_reply(conn_id, raw)

    def send_error_native(self, conn_id: int, msg: str) -> None:
        """Deferred fe error frame (RPCError(msg) at the caller)."""
        self._send_reply(conn_id, wire.encode_error(msg))

    def start(self) -> "NativeServer":
        with self._lock:
            if self._dead or self._srv is not None:
                return self
            self._srv = self._lib.rpcsrv_start(self.addr.encode(),
                                               self._seed, self._cb)
            if not self._srv:
                raise RPCError(f"native transport failed to bind {self.addr}")
            if self._unreliable:  # flag set before start
                self._lib.rpcsrv_set_unreliable(self._srv, 1)
        return self

    def set_unreliable(self, flag: bool) -> None:
        with self._lock:
            self._unreliable = bool(flag)
            if self._srv is not None and not self._dead:
                self._lib.rpcsrv_set_unreliable(self._srv, 1 if flag else 0)

    @property
    def rpc_count(self) -> int:
        with self._lock:
            if self._srv is not None and not self._dead:
                return int(self._lib.rpcsrv_rpc_count(self._srv))
            return self._final_rpc_count  # post-kill reads stay valid

    def deafen(self) -> None:
        """Reversible deafness, same contract as transport.Server: the
        socket path is renamed aside in Python (the C++ loop keeps its
        bound inode and never touches the path again), so undeafen() can
        restore it.  The lib's rpcsrv_deafen (one-way unlink) is no
        longer used — rename gives identical dial-failure semantics."""
        with self._lock:
            if self._srv is not None and not self._dead:
                try:
                    os.rename(self.addr, self.addr + ".deaf")
                except FileNotFoundError:
                    pass

    def undeafen(self) -> None:
        with self._lock:
            try:
                os.rename(self.addr + ".deaf", self.addr)
            except FileNotFoundError:
                pass

    def kill(self) -> None:
        with self._lock:
            if self._dead:
                return
            self._dead = True
            if self._srv is not None:
                self._final_rpc_count = int(
                    self._lib.rpcsrv_rpc_count(self._srv))
                self._lib.rpcsrv_kill(self._srv)
                try:  # a deafened server's bound inode lives at .deaf
                    os.unlink(self.addr + ".deaf")
                except FileNotFoundError:
                    pass
                # kill joined the loop → no new callbacks; the lock ensures
                # no in-flight _send_reply still holds the old pointer.
                self._lib.rpcsrv_free(self._srv)
                self._srv = None

    # ------------------------------------------------------------ plumbing

    def _on_request(self, conn_id: int, data, length: int) -> None:
        # Runs on the C++ loop thread (ctypes grabs the GIL): copy out and
        # hand off so the loop returns to epoll immediately.  One thread per
        # in-flight request — the Python accept loop's semantics, so N
        # concurrently blocking handlers never starve request N+1.
        # With inline handlers registered, the frame is decoded HERE and an
        # inline rpc is served on this thread (decode + enqueue + wake; the
        # event-loop discipline) — zero handler threads on the batched path.
        payload = ctypes.string_at(data, length)
        if wire.is_fe_frame(payload):
            # Versioned fe wire frame reaching PYTHON: the C++ ingest is
            # off (custom op factory, or a pre-ingest lib).  Decode with
            # the shared schema and serve — same layout, different
            # decoder, so fallback parity holds.
            self._serve_native(conn_id, payload)
            return
        frame = None
        if self._inline:
            try:
                frame = pickle.loads(payload)
                fn = self._inline.get(frame[0])
            except Exception:  # undecodable frame: drop (cf. _serve)
                transport._M_WIRE_REJ.inc(key="undecodable")
                self._send_reply(conn_id, b"")
                return
            if fn is not None:
                try:
                    fn(conn_id, frame[1],
                       frame[2] if len(frame) > 2 else None)
                except Exception as e:  # noqa: BLE001 — loop must survive
                    crashsink.record("native-rpc-inline", e, fatal=False)
                    self._send_reply(conn_id, b"")
                return
            # Non-inline rpc on a mixed server: hand the ALREADY-decoded
            # frame to the worker (never decode twice).
        threading.Thread(
            target=crashsink.guarded(self._serve, "native-rpc-serve"),
            args=(conn_id, payload, frame), daemon=True).start()

    def _serve_native(self, conn_id: int, payload: bytes) -> None:
        """fe wire frame, Python side: inline to the native-batch engine
        hook when registered, else a worker thread over the blocking
        fe_batch handler; replies always go back in the fe layout the
        request arrived in."""
        try:
            ops, tc, meta = wire.decode_batch_meta(payload)
        except RPCError as e:
            # Malformed (incl. CRC mismatch): connection-scoped error,
            # counted, never a crash or a mis-applied op.
            transport._M_WIRE_REJ.inc(key="malformed_fe")
            self._send_reply(conn_id, wire.encode_error(str(e)))
            return
        nb = self._native_batch
        if nb is not None:
            try:
                nb(conn_id, ops, tc, meta)
            except Exception as e:  # noqa: BLE001 — loop must survive
                crashsink.record("native-rpc-inline", e, fatal=False)
                self._send_reply(conn_id, b"")
            return
        fn = self._handlers.get("fe_batch")
        if fn is None:
            self._send_reply(
                conn_id, wire.encode_error("no such rpc: fe_batch"))
            return
        threading.Thread(
            target=crashsink.guarded(self._serve_native_blocking,
                                     "native-rpc-serve"),
            args=(conn_id, fn, ops, tc, meta), daemon=True).start()

    def _serve_native_blocking(self, conn_id, fn, ops, tc, meta) -> None:
        try:
            if tc is not None:
                with _tracing.use_ctx(_tracing.TraceContext(*tc)):
                    replies = fn(ops)
            else:
                replies = fn(ops)
        except RPCError:
            self._send_reply(conn_id, b"")  # refusal: drop, no reply
            return
        except Exception as e:  # app-level error → fe error frame
            self._send_reply(conn_id, wire.encode_error(f"{e!r:.200}"))
            return
        try:
            raw = wire.encode_replies(replies, crc=meta.get("crc", False))
            if len(raw) > transport._MAX_FRAME:
                # Cap parity with the reply ring: explicit error, never
                # an oversized frame the client cap would reject.
                raw = wire.encode_error("reply too large for one fe frame")
        except Exception as e:  # noqa: BLE001 — degrade like _serve does
            raw = wire.encode_error(f"unserializable reply ({e!r:.100})")
        self._send_reply(conn_id, raw)

    def _serve(self, conn_id: int, payload: bytes, frame=None) -> None:
        try:
            if frame is None:
                frame = pickle.loads(payload)
            # Optional third element: a tpuscope TraceContext from a
            # tracing-enabled peer (transport.call's envelope; untagged
            # 2-tuples are the common wire).
            rpcname, args = frame[0], frame[1]
            wctx = frame[2] if len(frame) > 2 else None
            fn = self._handlers.get(rpcname)
            if fn is None:
                reply = (False, f"no such rpc: {rpcname}")
            else:
                try:
                    if wctx is not None:
                        with _tracing.use_ctx(_tracing.TraceContext(*wctx)):
                            reply = (True, fn(*args))
                    else:
                        reply = (True, fn(*args))
                except RPCError:
                    # Drop the connection without replying, as
                    # transport.Server does (zero-length = close marker).
                    self._send_reply(conn_id, b"")
                    return
                except Exception as e:
                    reply = (False, e)
        # tpusan: ok(daemon-bare-except) — undecodable frame is a
        # protocol-level drop answered with the close marker, not a
        # thread death; the client sees the dead connection and retries.
        except Exception:
            self._send_reply(conn_id, b"")  # undecodable frame: drop
            return
        try:
            raw = pickle.dumps(reply, protocol=pickle.HIGHEST_PROTOCOL)
        except Exception as e:
            raw = pickle.dumps(
                (False, f"unserializable reply ({e!r:.100})"),
                protocol=pickle.HIGHEST_PROTOCOL)
        self._send_reply(conn_id, raw)

    def _send_reply(self, conn_id: int, raw: bytes) -> None:
        buf = (ctypes.c_uint8 * len(raw)).from_buffer_copy(raw)
        with self._lock:
            if self._dead or self._srv is None:
                return
            self._lib.rpcsrv_reply(self._srv, conn_id, buf, len(raw))


class NativeIngest:
    """Python handle on a server's zero-GIL ingest state: reusable poll
    buffers (numpy, pointer-passed — the zero-copy handoff), the reply
    ring's write side, and the lazy id→str key mirror.

    Single-consumer by design: the frontend ENGINE thread owns poll/
    pending/fail/reap/decref; push/val_intern are safe from any thread
    (the driver's notify sweep calls them under the server mutex).  All
    C calls run with the raw server handle the wrapper captured at
    enable time — the frontend joins the engine before killing the
    server, so no call can outlive the handle."""

    REAP_CAP = 1024

    def __init__(self, srv: NativeServer):
        import numpy as np

        self._np = np
        self._srv = srv
        self._lib = srv._lib
        self._h = srv._srv
        self._lock = srv._lock  # serializes every C call vs kill/free
        self.fd = srv._ingest_fd
        self._cap = 0
        self._grow(4096)
        # hdr8: {frame_id, conn_id, nops, has_tc, tc0, tc1, deadline_ms,
        # ts_ns} — a stale .so writes only the first 7; slot 7 stays 0
        # and the engine falls back to its own poll instant.
        self._hdr = np.zeros(8, dtype=np.uint64)
        self._hdr_p = self._hdr.ctypes.data
        self._reap_buf = np.zeros(self.REAP_CAP, dtype=np.uint64)
        self._reap_p = self._reap_buf.ctypes.data
        self._scratch = ctypes.create_string_buffer(1 << 16)
        self._keystr: dict[int, str] = {}  # lazy id→str key mirror
        self._stats_buf = np.zeros(9, dtype=np.int64)
        self._stats_p = self._stats_buf.ctypes.data
        self._flush_buf = np.zeros(66, dtype=np.int64)
        self._flush_p = self._flush_buf.ctypes.data

    def _grow(self, cap: int) -> None:
        np = self._np
        self._cap = cap
        self._kind = np.zeros(cap, dtype=np.int32)
        self._cid = np.zeros(cap, dtype=np.int64)
        self._cseq = np.zeros(cap, dtype=np.int64)
        self._keyid = np.zeros(cap, dtype=np.int32)
        self._valid = np.zeros(cap, dtype=np.int32)
        self._pend = np.zeros(cap, dtype=np.int32)
        self._kind_p = self._kind.ctypes.data
        self._cid_p = self._cid.ctypes.data
        self._cseq_p = self._cseq.ctypes.data
        self._keyid_p = self._keyid.ctypes.data
        self._valid_p = self._valid.ctypes.data
        self._pend_p = self._pend.ctypes.data

    # ------------------------------------------------------------- ingest

    def poll1(self):
        """One ready frame as (frame_id, conn_id, nops, tc, deadline_ms,
        ts_ns, kind, cid, cseq, key_id, val_id) with engine-owned column
        copies, or None.  deadline_ms is the clerk op budget the frame
        header propagated (0 = none); ts_ns is the loop thread's
        frame-parse monotonic stamp — opscope's waterfall origin (0 on
        a stale .so; the engine substitutes its poll instant)."""
        while True:
            with self._lock:
                if self._srv._dead or self._srv._srv is None:
                    return None
                n = self._lib.rpcsrv_ingest_poll1(
                    self._h, self._hdr_p, self._kind_p, self._cid_p,
                    self._cseq_p, self._keyid_p, self._valid_p, self._cap)
            if n == -2:
                self._grow(self._cap * 2)
                continue
            if n < 0:
                return None
            n = int(n)
            h = self._hdr
            tc = (int(h[4]), int(h[5])) if h[3] else None
            return (int(h[0]), int(h[1]), n, tc, int(h[6]), int(h[7]),
                    self._kind[:n].copy(), self._cid[:n].copy(),
                    self._cseq[:n].copy(), self._keyid[:n].copy(),
                    self._valid[:n].copy())

    def scope_flush(self):
        """The C++ flush-stage histogram, CUMULATIVE: a 66-slot int64
        copy (64 log2-µs buckets, count, µs sum), or None when the
        loaded lib predates the opscope ABI.  The engine diffs against
        its previous copy and merges the delta into the registry once
        per pass."""
        if not hasattr(self._lib, "rpcsrv_opscope_flush"):
            return None
        with self._lock:
            if self._srv._dead or self._srv._srv is None:
                return None
            self._lib.rpcsrv_opscope_flush(self._h, self._flush_p)
        return self._flush_buf.copy()

    def push(self, tags, errs, repvals) -> None:
        """Reply-ring write: int64/uint8/int32 arrays of equal length."""
        n = len(tags)
        if not n:
            return
        with self._lock:
            if self._srv._dead or self._srv._srv is None:
                return
            self._lib.rpcsrv_ingest_push(
                self._h, tags.ctypes.data, errs.ctypes.data,
                repvals.ctypes.data, n)

    def val_intern(self, data: bytes) -> int:
        with self._lock:
            if self._srv._dead or self._srv._srv is None:
                return -1
            return int(self._lib.rpcsrv_ingest_val_intern(
                self._h, data, len(data)))

    def val_intern_many(self, values):
        """Intern a list of byte values in ONE C call (the notify
        sweep's get replies): returns an np.int32 id array."""
        np = self._np
        n = len(values)
        lens = np.fromiter((len(v) for v in values), dtype=np.int64,
                           count=n)
        offs = np.zeros(n, dtype=np.int64)
        np.cumsum(lens[:-1], out=offs[1:])
        out = np.empty(n, dtype=np.int32)
        data = b"".join(values)
        with self._lock:
            if self._srv._dead or self._srv._srv is None:
                out[:] = -1
                return out
            self._lib.rpcsrv_ingest_val_intern_many(
                self._h, data, offs.ctypes.data, lens.ctypes.data,
                out.ctypes.data, n)
        return out

    def pending(self, frame_id: int):
        """Unanswered slot indices (np.int32 copy), or None if unknown."""
        with self._lock:
            if self._srv._dead or self._srv._srv is None:
                return None
            n = self._lib.rpcsrv_ingest_pending(self._h, frame_id,
                                                self._pend_p)
        if n < 0:
            return None
        return self._pend[:int(n)].copy()

    def fail(self, frame_id: int, msg: str) -> None:
        with self._lock:
            if self._srv._dead or self._srv._srv is None:
                return
            self._lib.rpcsrv_ingest_fail(self._h, frame_id,
                                         msg.encode(errors="replace"))

    def reap(self) -> list:
        out = []
        while True:
            with self._lock:
                if self._srv._dead or self._srv._srv is None:
                    return out
                n = int(self._lib.rpcsrv_ingest_reap(
                    self._h, self._reap_p, self.REAP_CAP))
            out.extend(int(x) for x in self._reap_buf[:n])
            if n < self.REAP_CAP:
                return out

    # ------------------------------------------------------ intern mirror

    def _get(self, which: int, vid: int):
        while True:
            with self._lock:
                if self._srv._dead or self._srv._srv is None:
                    return None
                n = self._lib.rpcsrv_ingest_get(self._h, which, vid,
                                                self._scratch,
                                                len(self._scratch))
            if n < 0:
                return None
            if n <= len(self._scratch):
                return self._scratch.raw[:n]
            self._scratch = ctypes.create_string_buffer(int(n))

    def key_str(self, kid: int):
        """id → key string, lazily mirrored (keys repeat; the mirror is
        invalidated by decref_keys exactly when an id frees)."""
        s = self._keystr.get(kid)
        if s is None:
            b = self._get(0, kid)
            if b is None:
                return None
            s = b.decode()
            self._keystr[kid] = s
        return s

    def val_str(self, vid: int):
        """id → value string; -1 is the empty value, unique values are
        not cached (one materialization per proposal)."""
        if vid < 0:
            return ""
        b = self._get(1, vid)
        return None if b is None else b.decode()

    def decref_keys(self, ids) -> None:
        self._decref(0, ids)

    def decref_vals(self, ids) -> None:
        self._decref(1, ids, invalidate=False)

    def _decref(self, which: int, ids, invalidate: bool = True) -> None:
        n = len(ids)
        if not n:
            return
        np = self._np
        freed = np.zeros(n, dtype=np.int32)
        with self._lock:
            if self._srv._dead or self._srv._srv is None:
                return
            nf = int(self._lib.rpcsrv_ingest_decref(
                self._h, which, ids.ctypes.data, n, freed.ctypes.data))
        if invalidate and nf:
            pop = self._keystr.pop
            for vid in freed[:nf].tolist():
                pop(vid, None)

    def stats(self) -> dict:
        with self._lock:
            if not (self._srv._dead or self._srv._srv is None):
                self._lib.rpcsrv_ingest_stats(self._h, self._stats_p)
        b = self._stats_buf
        return {"frames": int(b[0]), "ops": int(b[1]), "bytes": int(b[2]),
                "ring_full": int(b[3]), "inflight_ops": int(b[4]),
                "live_frames": int(b[5]), "keys_live": int(b[6]),
                "vals_live": int(b[7]), "done_ops": int(b[8])}


def make_server(addr: str, seed: int | None = None, prefer_native=True):
    """Native event-loop server when the toolchain allows, else the Python
    accept-loop server — same surface either way.  NOT yet started: register
    handlers, then call .start() (register-before-expose, so a dialer never
    reaches a socket with no handlers behind it)."""
    if prefer_native and native_available():
        return NativeServer(addr, seed=seed)
    return transport.Server(addr, seed=seed)
