from tpu6824.rpc.transport import (
    Proxy,
    Server,
    call,
    connect,
    link_alias,
    unlink_alias,
)

__all__ = ["Proxy", "Server", "call", "connect", "link_alias", "unlink_alias"]
