from tpu6824.rpc.transport import (
    DelayProxy,
    Proxy,
    Server,
    call,
    connect,
    link_alias,
    unlink_alias,
)

__all__ = ["DelayProxy", "Proxy", "Server", "call", "connect", "link_alias", "unlink_alias"]
