"""L0 transport — RPC over Unix-domain sockets, pooled by default.

Capability parity with the reference's transport layer: the `call()` helper
duplicated in every package (`paxos/rpc.go:24-42`, `lockservice/client.go:42-57`,
…) plus the per-server accept loops that double as the fault-injection point
(`paxos/paxos.go:524-552`).

Connection discipline (ISSUE 1 satellite — bench r05: 1519.9 vs 571.1
decided/sec): `call()` reuses POOLED long-lived connections by default
(Go's `rpc.Client` model); the reference's literal dial-per-call discipline
stays available via `TPU6824_DIAL_PER_CALL=1` or `call(..., pooled=False)`
for reference-runtime-fidelity runs.  The harness's filesystem surgery
keeps working under pooling because a pooled connection carries the
(st_dev, st_ino) identity of the socket path it dialed and is revalidated
against a fresh stat() before every reuse: `deafen()`/`kill()` unlink the
path (stat fails → the cached connection is discarded and the call fails
like a dial error), and `link_alias`/LinkFarm re-points resolve to a
different inode (stale connections to the old server are discarded and the
call re-dials the new one).  Fault injection stays per-REQUEST: the server
draws its accept-loop coins per frame, and every injected fault tears the
connection down, so an unreliable server costs pooled clients a redial —
exactly the reference's per-connection economics.

Properties the reference's tests depend on, all reproduced here:

  - `call()` fails on dial/IO error; "no reply" does NOT mean "not executed" —
    at-most-once is built ABOVE the transport, never in it
    (`lockservice/client.go:26-40` spells out the contract).
  - Server identity is a filesystem pathname, which makes network topology
    mutable via the filesystem: unlink a server's socket to deafen it
    (`paxos/test_test.go:194-195`), hard-link per-(src,dst) alias paths to
    build asymmetric partitions (`paxos/test_test.go:712-751`).
  - Unreliable mode lives in the accept loop: a fraction of connections is
    discarded unprocessed, and a further fraction is processed but the reply
    is discarded by shutting down the write side (`paxos/paxos.go:528-544`,
    SHUT_WR — the executed-but-unacked case).

Wire format (ours, not the reference's gob): 4-byte big-endian length prefix +
pickled `(rpcname, args)` request, pickled `(ok, payload)` reply.  The codec is
host-control-plane only — consensus payloads on the TPU path travel as
interned int32 ids, never through this socket (SURVEY §2.3).
"""

from __future__ import annotations

import os
import pickle
import random
import socket
import struct
import threading
import time

from tpu6824.obs import metrics as _metrics
from tpu6824.obs import opscope as _opscope
from tpu6824.obs import tracing as _tracing
from tpu6824.rpc import netfault as _netfault
from tpu6824.rpc import wire
from tpu6824.utils.errors import RPCError
from tpu6824.utils import crashsink
from tpu6824.utils.trace import dprintf

# Reference accept-loop fault rates (paxos/paxos.go:528-544).
REQ_DROP = 0.10
REP_DROP = 0.20

# tpuscope metrics (created HERE, at module scope — the tpusan
# metric-unregistered contract): per-method client call/failure counts +
# latency histogram, and the server-side fault-coin outcomes that used
# to be invisible (a dropped reply looked identical to a dead server).
_M_CALLS = _metrics.counter("rpc.client.calls")
_M_FAILS = _metrics.counter("rpc.client.failures")
_M_LAT = _metrics.histogram("rpc.client.latency_us")
_M_SRV_REQS = _metrics.counter("rpc.server.requests")
_M_SRV_DROP_REQ = _metrics.counter("rpc.server.dropped_requests")
_M_SRV_DROP_REP = _metrics.counter("rpc.server.dropped_replies")
# Connection-pool economics (ISSUE 8 satellite): reuse vs redial vs
# eviction, so a frontend leg's per-leg tpuscope delta shows whether its
# connections actually persisted.  Eviction reasons ride the per-key
# breakdown (stale identity / aged out / liveness fail / cap overflow).
_M_POOL_HITS = _metrics.counter("rpc.pool.hits")
_M_POOL_MISSES = _metrics.counter("rpc.pool.misses")
_M_POOL_EVICT = _metrics.counter("rpc.pool.evictions")
# Decode state-machine rejects (ISSUE 12, netfault): malformed,
# truncated, oversized, or CRC-failed input handled as a CONNECTION-
# scoped error — counted by reason, never a crash, a livelock, or a
# wire-format demotion.  Shared by both transports' Python paths; the
# C++ loop keeps its own counter (NativeServer.wire_rejected).
_M_WIRE_REJ = _metrics.counter("rpc.wire.rejected")

_LEN = struct.Struct(">I")
_MAX_FRAME = 64 << 20

# Slow-loris bound (netfault `stall` defense): one frame must finish
# arriving within this window or the connection is closed — per FRAME,
# not per recv(), so a trickling peer cannot pin a serving thread and
# its buffer indefinitely by staying just under the socket timeout.
READ_DEADLINE = float(os.environ.get("TPU6824_WIRE_READ_DEADLINE", 30.0))

# Pooled persistent connections are the default (see module docstring);
# TPU6824_DIAL_PER_CALL=1 restores the reference's dial-per-call discipline
# process-wide (per-call override: call(..., pooled=...)).
POOLED_DEFAULT = os.environ.get(
    "TPU6824_DIAL_PER_CALL", "") not in ("1", "true", "yes")
_POOL_MAX_IDLE = 8     # cached idle connections per addr
_POOL_MAX_AGE = 10.0   # s; below the server's 30s read timeout, so a
#                        reused connection is never one the server already
#                        timed out (which would look like a lost reply)


class _ConnPool:
    """addr → idle persistent connections, each tagged with the socket
    path's (st_dev, st_ino) at dial time.  `borrow` revalidates identity
    against a fresh stat and liveness with a zero-byte MSG_PEEK, so
    filesystem surgery (deafen/alias re-point/server restart) and
    server-side closes are observed before a request is risked on a stale
    connection."""

    _MAX_TOTAL = 256  # global idle-FD cap across every addr

    def __init__(self):
        self._lock = threading.Lock()
        self._idle: dict[str, list] = {}  # addr -> [(sock, ident, t_idle)]
        self._total = 0
        self._pid = os.getpid()

    def _fork_guard_locked(self) -> None:
        # A forked child inherits dup'd pool FDs; sharing them with the
        # parent would interleave frames on one stream.  Drop (and close —
        # closing a dup never disturbs the parent's copy) everything
        # cached by another pid.
        if self._pid != os.getpid():
            self._pid = os.getpid()
            for entries in self._idle.values():
                for sock, _, _ in entries:
                    self._close(sock)
            self._idle.clear()
            self._total = 0

    @staticmethod
    def _ident(addr: str):
        st = os.stat(addr)  # OSError propagates: the dial-failure case
        return (st.st_dev, st.st_ino)

    def borrow(self, addr: str):
        """(sock, ident) of a validated cached connection, or (None, ident)
        when the caller must dial.  Raises OSError if `addr` is gone."""
        ident = self._ident(addr)
        now = time.monotonic()
        while True:
            with self._lock:
                self._fork_guard_locked()
                entries = self._idle.get(addr)
                if not entries:
                    _M_POOL_MISSES.inc()
                    return None, ident
                sock, sid, t = entries.pop()
                self._total -= 1
            if sid != ident or now - t > _POOL_MAX_AGE:
                self._close(sock)
                _M_POOL_EVICT.inc(
                    key="stale" if sid != ident else "aged")
                continue
            try:  # liveness peek: EOF/reset from a dead server shows here
                sock.setblocking(False)
                try:
                    if sock.recv(1, socket.MSG_PEEK) == b"":
                        self._close(sock)
                        _M_POOL_EVICT.inc(key="liveness")
                        continue
                    # Unexpected readable bytes on an idle conn: protocol
                    # desync — never reuse it.
                    self._close(sock)
                    _M_POOL_EVICT.inc(key="liveness")
                    continue
                except (BlockingIOError, InterruptedError):
                    pass  # no data, still open: healthy
                finally:
                    sock.setblocking(True)
            except OSError:
                self._close(sock)
                _M_POOL_EVICT.inc(key="liveness")
                continue
            _M_POOL_HITS.inc()
            return sock, ident

    def give(self, addr: str, sock, ident) -> None:
        evicted = []
        with self._lock:
            self._fork_guard_locked()
            entries = self._idle.setdefault(addr, [])
            if len(entries) >= _POOL_MAX_IDLE:
                self._close(sock)
                _M_POOL_EVICT.inc(key="cap")
                return
            entries.append((sock, ident, time.monotonic()))
            self._total += 1
            if self._total > self._MAX_TOTAL:
                # HARD FD-cap eviction: age out stale entries first
                # (long-dead addrs from torn-down harness clusters), then
                # — the cap is a cap, not a hint — drop oldest-idle
                # entries until back under it, so a deployment with many
                # busy sockets cannot climb to EMFILE 8 fresh conns per
                # addr at a time.
                now = time.monotonic()
                for a in list(self._idle):
                    kept = [e for e in self._idle[a]
                            if now - e[2] <= _POOL_MAX_AGE]
                    evicted.extend(e[0] for e in self._idle[a]
                                   if now - e[2] > _POOL_MAX_AGE)
                    if kept:
                        self._idle[a] = kept
                    else:
                        del self._idle[a]
                self._total -= len(evicted)
                if self._total > self._MAX_TOTAL:
                    flat = sorted(
                        ((e[2], a, e) for a in self._idle
                         for e in self._idle[a]),
                        key=lambda t: t[0])
                    drop = flat[:self._total - self._MAX_TOTAL]
                    for _, a, e in drop:
                        self._idle[a].remove(e)
                        if not self._idle[a]:
                            del self._idle[a]
                        evicted.append(e[0])
                        self._total -= 1
        if evicted:
            _M_POOL_EVICT.inc(len(evicted), key="cap")
        for s in evicted:
            self._close(s)

    def close_all(self) -> None:
        with self._lock:
            idle, self._idle = self._idle, {}
            self._total = 0
        for entries in idle.values():
            for sock, _, _ in entries:
                self._close(sock)

    @staticmethod
    def _close(sock) -> None:
        try:
            sock.close()
        except OSError:
            pass


_pool = _ConnPool()


def reset_pool() -> None:
    """Drop every cached client connection (test isolation helper)."""
    _pool.close_all()


def _send_raw_frame(sock: socket.socket, data: bytes) -> None:
    if len(data) > _MAX_FRAME:
        raise RPCError(f"frame too large to send: {len(data)}")
    sock.sendall(_LEN.pack(len(data)) + data)


def _send_frame(sock: socket.socket, obj) -> None:
    _send_raw_frame(sock,
                    pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL))


def _recv_exact(sock: socket.socket, n: int,
                deadline: float | None = None) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        if deadline is not None:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                _M_WIRE_REJ.inc(key="read_deadline")
                raise RPCError("frame read deadline exceeded (slow peer)")
            sock.settimeout(min(30.0, remaining))
        try:
            chunk = sock.recv(n - len(buf))
        except socket.timeout:
            if deadline is None:
                raise
            continue  # re-check the frame deadline at the loop top
        if not chunk:
            raise RPCError("connection closed mid-frame")
        buf += chunk
    return bytes(buf)


def _recv_raw_frame(sock: socket.socket) -> bytes:
    (n,) = _LEN.unpack(_recv_exact(sock, _LEN.size))
    if n > _MAX_FRAME:
        raise RPCError(f"frame too large: {n}")
    return _recv_exact(sock, n)


def _recv_raw_frame_server(sock: socket.socket) -> bytes:
    """The SERVER's framed read: the idle wait for a next request is
    bounded by the socket timeout as before, but once the first byte of
    a frame arrives the whole frame must complete within READ_DEADLINE
    (netfault `stall` defense — a slow-loris trickling bytes just under
    the socket timeout used to pin the serving thread indefinitely);
    the rolling buffer stays bounded by the frame cap either way."""
    sock.settimeout(30.0)
    first = _recv_exact(sock, 1)
    deadline = time.monotonic() + READ_DEADLINE
    (n,) = _LEN.unpack(first + _recv_exact(sock, _LEN.size - 1, deadline))
    if n > _MAX_FRAME:
        _M_WIRE_REJ.inc(key="oversized")
        raise RPCError(f"frame too large: {n}")
    body = _recv_exact(sock, n, deadline)
    # Restore the serving timeout NOW, not at the next read: the frame
    # may have completed with only milliseconds of deadline left, and
    # the handler's reply sendall runs on this same socket — it must
    # not inherit a near-expired recv clamp.
    sock.settimeout(30.0)
    return body


def _unpickle_frame(data: bytes):
    try:
        return pickle.loads(data)
    except Exception as e:  # corrupt frame or a non-round-trippable payload
        raise RPCError(f"undecodable frame: {e!r}") from e


def _recv_frame(sock: socket.socket):
    return _unpickle_frame(_recv_raw_frame(sock))


class FramedConn:
    """One persistent framed connection with BUFFERED, batched reads —
    the client leg of the clerk-frontend protocol (services/frontend.py).

    `transport.call` pays two recv() syscalls per reply (length, then
    payload) and re-enters the pool per request; a frontend clerk keeps
    one of these per connection instead: `send()` writes a frame,
    `recv()` decodes the next frame out of a rolling buffer that is
    refilled 64KB at a time — so a burst of replies (or one multi-op
    reply riding with the next) costs one syscall, not two per frame.
    Single-threaded per instance (one event-loop/driver owns it); any
    IO failure raises RPCError and the connection is garbage — redial,
    exactly the transport contract (the op may or may not have run)."""

    __slots__ = ("addr", "sock", "_buf", "_nf", "_nf_hold")

    def __init__(self, addr: str, timeout: float = 10.0):
        self.addr = addr
        # netfault (ISSUE 12): a WireFault registered over this address
        # intercepts every framed send — byte-level fault injection at
        # the one client-side transport seam.  Looked up at dial time
        # (the harness registers scopes before clerks dial).
        self._nf = _netfault.for_addr(addr)
        self._nf_hold = bytearray() if self._nf is not None else None
        self.sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        try:
            self.sock.settimeout(timeout)
            self.sock.connect(addr)
        except OSError as e:
            self._close_sock()
            raise RPCError(f"dial {addr}: {e}") from e
        self._buf = bytearray()

    def fileno(self) -> int:
        return self.sock.fileno()

    def settimeout(self, t: float | None) -> None:
        self.sock.settimeout(t)

    def send(self, obj) -> None:
        self.send_raw(pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL))

    def send_raw(self, data: bytes) -> None:
        """Send a pre-encoded frame body (the versioned fe wire layout —
        rpc/wire.py — travels as raw bytes, not pickle)."""
        if len(data) > _MAX_FRAME:
            raise RPCError(f"frame too large to send: {len(data)}")
        framed = _LEN.pack(len(data)) + data
        try:
            if self._nf is not None:
                self._nf.send(self.sock, framed, hold=self._nf_hold)
            else:
                self.sock.sendall(framed)
        except OSError as e:  # ConnectionError from an injected tear too
            raise RPCError(f"send {self.addr}: {e}") from e

    def _pop_frame(self):
        """Decode one frame from the buffer, or None if incomplete."""
        buf = self._buf
        if len(buf) < _LEN.size:
            return None
        (n,) = _LEN.unpack_from(buf)
        if n > _MAX_FRAME:
            _M_WIRE_REJ.inc(key="oversized")
            raise RPCError(f"frame too large: {n}")
        if len(buf) < _LEN.size + n:
            return None
        data = bytes(buf[_LEN.size:_LEN.size + n])
        del buf[:_LEN.size + n]
        if wire.is_fe_frame(data):
            # fe wire reply/error frame: decoded by the shared schema
            # into the same (ok, payload) shape pickled replies carry.
            # A malformed/CRC-failed reply is a CONNECTION-scoped
            # reject: counted, the caller tears and redials.
            try:
                return (wire.decode_any_reply(data),)
            except RPCError:
                _M_WIRE_REJ.inc(key="malformed_fe")
                raise
        try:
            return (pickle.loads(data),)
        except Exception as e:
            _M_WIRE_REJ.inc(key="undecodable")
            raise RPCError(f"undecodable frame: {e!r}") from e

    def recv(self):
        """Next reply frame (blocking up to the socket timeout)."""
        while True:
            got = self._pop_frame()
            if got is not None:
                return got[0]
            try:
                chunk = self.sock.recv(65536)
            except OSError as e:
                raise RPCError(f"recv {self.addr}: {e}") from e
            if not chunk:
                raise RPCError("connection closed mid-frame")
            self._buf += chunk

    def request(self, obj):
        """send + recv: one frame round-trip."""
        self.send(obj)
        return self.recv()

    def close(self) -> None:
        self._close_sock()

    def _close_sock(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass


def call(addr: str, rpcname: str, *args, timeout: float = 10.0,
         pooled: bool | None = None):
    """Invoke `rpcname(*args)` at `addr` and return the result — over a
    pooled persistent connection by default, or dial-per-call with
    `pooled=False` / `TPU6824_DIAL_PER_CALL=1` (the reference's exact
    discipline; see the module docstring for how pooling preserves the
    harness's surgery and fault semantics).

    Raises RPCError on any failure — dial error, connection reset, reply
    discarded by an unreliable server.  Per the transport contract the op may
    or may not have executed when this raises (`lockservice/client.go:26-40`)
    — a failed pooled request is NEVER transparently retried, precisely so
    at-most-once stays the caller's job as the contract spells out.
    Application-level errors raised by the handler are re-raised verbatim.

    Trace propagation (tpuscope): when tracing is enabled and the calling
    thread carries a TraceContext, the request frame grows an optional
    THIRD element `(trace_id, span_id)` and the call is wrapped in an
    `rpc.call` span.  Untraced calls (the default) send the classic
    2-tuple, so the wire is unchanged — backward-compatible with
    untagged peers in both directions.
    """
    if pooled is None:
        pooled = POOLED_DEFAULT
    sock = ident = None
    sp = _tracing.child("rpc.call", comp="rpc", method=rpcname) \
        if _tracing.enabled() else None
    t0 = time.perf_counter_ns()
    _M_CALLS.inc(key=rpcname)
    try:
        try:
            if pooled:
                try:
                    sock, ident = _pool.borrow(addr)
                except OSError as e:  # socket path gone: the dial failure
                    raise RPCError(f"call {rpcname}@{addr}: {e}") from e
            if sock is None:
                sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
                sock.settimeout(timeout)
                sock.connect(addr)
            else:
                sock.settimeout(timeout)
            if sp is not None:
                _send_frame(sock, (rpcname, args,
                                   (sp.trace_id, sp.span_id)))
            else:
                _send_frame(sock, (rpcname, args))
            ok, payload = _recv_frame(sock)
        except RPCError:
            raise
        except OSError as e:
            raise RPCError(f"call {rpcname}@{addr}: {e}") from e
        if pooled:
            _pool.give(addr, sock, ident)
            sock = None  # returned healthy — don't close below
        if ok:
            _M_LAT.observe((time.perf_counter_ns() - t0) // 1000,
                           key=rpcname)
            return payload
        if isinstance(payload, BaseException):
            raise payload
        raise RPCError(f"{rpcname}@{addr}: {payload}")
    except RPCError as e:
        _M_FAILS.inc(key=rpcname)
        dprintf("rpc", "call %s@%s failed: %s", rpcname, addr, e)
        raise
    finally:
        if sp is not None:
            sp.end()
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass


def exported_methods(obj, methods: list[str] | None = None) -> list[str]:
    """The RPC export policy, shared by every server backend.  Precedence:
    explicit `methods` > the object's `RPC_METHODS` attribute > all public
    callables minus the lifecycle denylist (Go's net/rpc excludes lifecycle
    methods via its signature filter; we use an explicit denylist)."""
    return methods or getattr(obj, "RPC_METHODS", None) or [
        m for m in dir(obj)
        if not m.startswith("_")
        and m not in Server._NEVER_EXPORT
        and callable(getattr(obj, m))
    ]


class Server:
    """One RPC endpoint on a Unix socket; the accept loop is the
    fault-injection point, exactly as in the reference (§ docstring above)."""

    def __init__(self, addr: str, seed: int | None = None):
        self.addr = addr
        try:
            os.unlink(addr)
        except FileNotFoundError:
            pass
        os.makedirs(os.path.dirname(addr) or ".", exist_ok=True)
        self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._sock.bind(addr)
        self._sock.listen(128)
        self._handlers: dict[str, callable] = {}
        self._dead = threading.Event()
        self._unreliable = False
        self._netfault = None  # WireFault over the reply path (ISSUE 12)
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        # Requests served (paxos/paxos.go:539-542 rpccount; under
        # dial-per-call clients this equals accepted connections, exactly
        # the reference's counter).  accept_count tracks raw connections —
        # the pooling win is visible as rpc_count >> accept_count.
        self.rpc_count = 0
        self.accept_count = 0
        self._live: set[socket.socket] = set()  # in-flight connections
        self._thread = threading.Thread(
            target=crashsink.guarded(self._accept_loop, "rpc-accept"),
            daemon=True)

    # ------------------------------------------------------------ lifecycle

    def register(self, name: str, fn) -> "Server":
        self._handlers[name] = fn
        return self

    # Lifecycle / fault-injection methods must never be dialable (Go's
    # net/rpc excludes them via its method-signature filter; we use an
    # explicit denylist + opt-in RPC_METHODS).
    _NEVER_EXPORT = frozenset(
        {"kill", "start", "stop", "deafen", "undeafen", "revive",
         "set_unreliable", "die_after_next_deaf"}
    )

    def register_obj(self, obj, methods: list[str] | None = None) -> "Server":
        """Expose an object's methods as RPCs (the net/rpc
        `rpcs.Register(px)` pattern, `paxos/paxos.go:496-516`)."""
        for m in exported_methods(obj, methods):
            self._handlers[m] = getattr(obj, m)
        return self

    def start(self) -> "Server":
        self._thread.start()
        return self

    def kill(self) -> None:
        """Clean shutdown: atomic dead flag + close listener + tear down
        live (possibly pooled-idle) connections (`paxos/paxos.go:456-461`)."""
        self._dead.set()
        try:
            self._sock.close()
        except OSError:
            pass
        for path in (self.addr, self.addr + ".deaf"):
            try:
                os.unlink(path)
            except FileNotFoundError:
                pass
        # Persistent connections may be parked in recv awaiting the next
        # request; close them so serving threads exit and pooled clients
        # see EOF instead of a 30s stall.
        with self._lock:
            live, self._live = list(self._live), set()
        for c in live:
            try:
                c.close()
            except OSError:
                pass

    # ------------------------------------------------------- fault injection

    def set_unreliable(self, flag: bool) -> None:
        with self._lock:
            self._unreliable = flag

    def set_netfault(self, wf) -> None:
        """Attach a netfault.WireFault over this server's REPLY path:
        every outbound reply frame consults it (byte-level injection on
        the server→client direction; the client side injects through
        FramedConn's registry lookup).  None detaches."""
        self._netfault = wf

    def _send_raw_reply(self, conn: socket.socket, data: bytes) -> None:
        """One framed reply, through the netfault seam when armed.
        Raises RPCError for an oversized frame BEFORE any bytes move
        (the stream stays clean); injected tears raise ConnectionError
        (an OSError), which callers already treat as a dead peer."""
        if len(data) > _MAX_FRAME:
            raise RPCError(f"frame too large to send: {len(data)}")
        framed = _LEN.pack(len(data)) + data
        wf = self._netfault
        if wf is not None:
            wf.send(conn, framed, dup_literal=False)
        else:
            conn.sendall(framed)

    def _send_obj_reply(self, conn: socket.socket, obj) -> None:
        self._send_raw_reply(
            conn, pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL))

    def deafen(self) -> None:
        """Remove the socket path out from under the live server: existing
        inode keeps listening but nobody can dial it
        (`paxos/test_test.go:194-195`).  The path is renamed aside rather
        than unlinked so `undeafen()` can restore it — semantically
        identical to dialers (the public path is gone either way; pooled
        clients fail their stat revalidation), but reversible, which is
        what lets the nemesis engine use deafness as a schedulable fault
        instead of a one-way door."""
        try:
            os.rename(self.addr, self.addr + ".deaf")
        except FileNotFoundError:
            pass  # already deaf, or killed

    def undeafen(self) -> None:
        """Restore a deafened server's public path (inverse of deafen);
        a no-op when not deaf."""
        try:
            os.rename(self.addr + ".deaf", self.addr)
        except FileNotFoundError:
            pass

    # ------------------------------------------------------------- serving

    def _accept_loop(self) -> None:
        while not self._dead.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                if not self._dead.is_set():
                    # Fail-stop, not zombie: without this, the listener's
                    # backlog keeps accepting connects that then hang until
                    # the client timeout.
                    self.kill()
                return
            if self._dead.is_set():
                conn.close()
                return
            with self._lock:
                self.accept_count += 1
                self._live.add(conn)
            t = threading.Thread(
                target=crashsink.guarded(self._serve_conn, "rpc-serve-conn"),
                args=(conn,), daemon=True
            )
            t.start()

    def _serve_conn(self, conn: socket.socket) -> None:
        """Serve frames on one connection until the client hangs up (a
        dial-per-call client sends exactly one).  The fault-injection coin
        flips happen per REQUEST — the accept-loop semantics at request
        granularity — and every injected fault tears the connection down,
        so a pooled client pays the same redial the reference's
        dial-per-call client would."""
        try:
            conn.settimeout(30.0)
            while not self._dead.is_set():
                try:
                    raw = _recv_raw_frame_server(conn)
                    native = wire.is_fe_frame(raw)
                    if native:
                        # Versioned fe wire frame (rpc/wire.py): the
                        # pure-Python server speaks the SAME layout the
                        # native ingest path does — fallback parity is a
                        # schema contract, not a degraded dialect.
                        rpcname, args, wctx = "fe_batch", None, None
                    else:
                        try:
                            frame = _unpickle_frame(raw)
                        except RPCError:
                            # Corrupt/garbled frame: connection-scoped
                            # reject — counted, conn closed, the server
                            # keeps serving everyone else.
                            _M_WIRE_REJ.inc(key="undecodable")
                            return
                        # Optional third element: a tpuscope TraceContext
                        # from a tracing-enabled peer (untagged 2-tuples
                        # are the common wire; see call()).
                        rpcname, args = frame[0], frame[1]
                        wctx = frame[2] if len(frame) > 2 else None
                except (RPCError, OSError):
                    return  # client hung up / idled out: connection done
                with self._lock:
                    self.rpc_count += 1
                    unrel = self._unreliable
                    r1 = self._rng.random()
                    r2 = self._rng.random()
                _M_SRV_REQS.inc(key=rpcname)
                if unrel and r1 < REQ_DROP:
                    # discard unprocessed (op NOT executed)
                    _M_SRV_DROP_REQ.inc(key=rpcname)
                    dprintf("rpc", "%s: dropped request %s (unreliable)",
                            self.addr, rpcname)
                    return
                discard_reply = unrel and r2 < REP_DROP
                if native:
                    if not self._serve_native_frame(conn, raw,
                                                    discard_reply):
                        return
                    continue
                fn = self._handlers.get(rpcname)
                if fn is None:
                    reply = (False, f"no such rpc: {rpcname}")
                else:
                    try:
                        if wctx is not None:
                            with _tracing.use_ctx(
                                    _tracing.TraceContext(*wctx)):
                                reply = (True, fn(*args))
                        else:
                            reply = (True, fn(*args))
                    except RPCError:
                        return  # transport-level refusal: drop, no reply
                    except Exception as e:  # app-level error → the caller
                        reply = (False, e)
                if discard_reply:
                    _M_SRV_DROP_REP.inc(key=rpcname)
                    dprintf("rpc", "%s: dropped reply %s (unreliable)",
                            self.addr, rpcname)
                    # Processed, but the client sees a dead connection — the
                    # SHUT_WR trick (paxos/paxos.go:535-538).
                    conn.shutdown(socket.SHUT_WR)
                    return
                try:
                    self._send_obj_reply(conn, reply)
                except OSError:
                    return  # peer gone / stream broken — nothing to salvage
                except Exception as e:
                    # Unpicklable or oversized reply: dumps/size-check fail
                    # before any bytes move, so the stream is still clean —
                    # degrade to a string error instead of a silent hang.
                    self._send_obj_reply(
                        conn, (False, f"unserializable reply ({e!r:.100}): "
                                      f"{reply[1]!r:.200}")
                    )
        except (RPCError, OSError):
            pass
        finally:
            with self._lock:
                self._live.discard(conn)
            conn.close()

    def _serve_native_frame(self, conn: socket.socket, raw: bytes,
                            discard_reply: bool) -> bool:
        """One fe wire frame on the blocking server: decode with the
        shared schema, run the registered `fe_batch` handler, reply in
        the SAME layout.  Returns False when the connection is done."""
        try:
            ops, tc, meta = wire.decode_batch_meta(raw)
        except RPCError as e:
            # Malformed (incl. CRC mismatch): counted, answered with an
            # explicit error — never a crash or a mis-applied op.
            _M_WIRE_REJ.inc(key="malformed_fe")
            self._send_raw_reply(conn, wire.encode_error(str(e)))
            return True
        fn = self._handlers.get("fe_batch")
        if fn is None:
            out = wire.encode_error("no such rpc: fe_batch")
        else:
            try:
                if tc is not None:
                    with _tracing.use_ctx(_tracing.TraceContext(*tc)):
                        replies = fn(ops)
                else:
                    replies = fn(ops)
                # opscope flush stage (ISSUE 15), blocking-server path:
                # reply serialize + socket send, one observation per
                # frame — the pure-Python fallback emits the SAME stage
                # name set as the C++ reply ring.
                t_ser = time.monotonic_ns() if _opscope.enabled() else 0
                out = wire.encode_replies(replies,
                                          crc=meta.get("crc", False))
            except RPCError:
                return False  # transport-level refusal: drop, no reply
            except Exception as e:  # app-level error → fe error frame
                t_ser = 0
                out = wire.encode_error(f"{e!r:.200}")
        if discard_reply:
            _M_SRV_DROP_REP.inc(key="fe_batch")
            dprintf("rpc", "%s: dropped reply fe_batch (unreliable)",
                    self.addr)
            conn.shutdown(socket.SHUT_WR)
            return False
        try:
            self._send_raw_reply(conn, out)
            if fn is not None and t_ser:
                _opscope.observe_flush(time.monotonic_ns() - t_ser)
        except RPCError:
            # Reply past the frame cap: the size check fires before any
            # bytes move, so the stream is clean — degrade to an error
            # frame (the pickled path's unserializable-reply contract;
            # a silent drop would retry-livelock the clerk).
            try:
                self._send_raw_reply(conn, wire.encode_error(
                    "reply too large for one fe frame"))
            except OSError:
                return False
        except OSError:
            return False
        return True


class DelayProxy:
    """Byte-copying proxy with an atomic delay knob — the reference swaps
    one of these in front of a live server (by renaming sockets) to test
    slow-network behavior without loss (`pbservice/test_test.go:897-954`).

    Each accepted connection dials `backend_addr` and copies bytes both
    ways; every chunk waits the current delay before being forwarded.  The
    knob can be turned while connections are in flight."""

    def __init__(self, listen_addr: str, backend_addr: str, delay: float = 0.0):
        self.addr = listen_addr
        self.backend = backend_addr
        self._delay = delay
        self._lock = threading.Lock()
        self._dead = threading.Event()
        self._live: set[socket.socket] = set()  # in-flight pump sockets
        try:
            os.unlink(listen_addr)
        except FileNotFoundError:
            pass
        self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._sock.bind(listen_addr)
        self._sock.listen(128)
        self._thread = threading.Thread(
            target=crashsink.guarded(self._accept_loop, "delay-proxy-accept"),
            daemon=True)

    def start(self) -> "DelayProxy":
        self._thread.start()
        return self

    def set_delay(self, seconds: float) -> None:
        with self._lock:
            self._delay = seconds

    @property
    def delay(self) -> float:
        with self._lock:
            return self._delay

    def kill(self) -> None:
        self._dead.set()
        try:
            self._sock.close()
        except OSError:
            pass
        try:
            os.unlink(self.addr)
        except FileNotFoundError:
            pass
        # Unblock pump threads stuck in recv on stalled peers.
        with self._lock:
            live, self._live = list(self._live), set()
        for s in live:
            try:
                s.close()
            except OSError:
                pass

    def _accept_loop(self) -> None:
        while not self._dead.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                if not self._dead.is_set():
                    self.kill()  # fail-stop, not zombie (cf. Server above)
                return
            conn.settimeout(30.0)
            try:
                up = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
                up.settimeout(30.0)
                up.connect(self.backend)
            except OSError:
                conn.close()
                continue
            with self._lock:
                self._live.update((conn, up))
            for src, dst in ((conn, up), (up, conn)):
                threading.Thread(
                    target=crashsink.guarded(self._pump, "delay-proxy-pump"),
                    args=(src, dst), daemon=True
                ).start()

    def _pump(self, src: socket.socket, dst: socket.socket) -> None:
        try:
            while not self._dead.is_set():
                data = src.recv(65536)
                if not data:
                    break
                time.sleep(self.delay)
                dst.sendall(data)
        except OSError:
            pass
        finally:
            # Half-close so the peer sees EOF for this direction only; the
            # other pump thread owns the reverse direction.
            for s, how in ((dst, socket.SHUT_WR), (src, socket.SHUT_RD)):
                try:
                    s.shutdown(how)
                except OSError:
                    pass
            with self._lock:
                self._live.discard(src)


def link_alias(real: str, alias: str) -> None:
    """Create `alias` → `real` so dialing the alias reaches the server.  The
    reference hard-links per-(src,dst) socket paths to build asymmetric
    partitions and re-points them live (`paxos/test_test.go:712-751`)."""
    try:
        os.unlink(alias)
    except FileNotFoundError:
        pass
    try:
        os.link(real, alias)
    except OSError:
        os.symlink(real, alias)


def unlink_alias(alias: str) -> None:
    try:
        os.unlink(alias)
    except FileNotFoundError:
        pass


class LinkFarm:
    """Per-(src, dst) dialing aliases over a set of real server sockets —
    the reference's partition link farm (`pp(tag, i, j)` alias paths wired
    by `part()`, `paxos/test_test.go:712-751`): peer src dials dst through
    its own alias edge, so partitions are per-edge, asymmetric if desired,
    and re-wireable while the cluster runs.

    Servers bind their real paths; each peer dials through `view(src)`.
    Self edges are wired like any other, though in-process peers usually
    bypass them (self-calls are function calls in the reference too).

    Edges are SYMLINKS, not the reference's hard links: a symlink resolves
    the real path at dial time, so `Server.deafen()` (unlink the real path)
    still deafens farm traffic, and a peer that crash+restarts on the same
    path (the persist_dir flow) is reachable through existing edges without
    re-wiring.  Hard links pin the old inode and get both of those wrong."""

    def __init__(self, sockdir: str, real_addrs: list[str],
                 connected: bool = True):
        os.makedirs(sockdir, exist_ok=True)
        self.dir = sockdir
        self.real = list(real_addrs)
        self.n = len(real_addrs)
        if connected:
            self.heal()

    def alias(self, src: int, dst: int) -> str:
        return os.path.join(self.dir, f"edge-{src}-{dst}")

    def view(self, src: int) -> list[str]:
        """The peers[] list peer `src` should dial through."""
        return [self.alias(src, d) for d in range(self.n)]

    def connect(self, src: int, dst: int) -> None:
        alias = self.alias(src, dst)
        unlink_alias(alias)
        os.symlink(self.real[dst], alias)

    def disconnect(self, src: int, dst: int) -> None:
        unlink_alias(self.alias(src, dst))

    def part(self, *groups) -> None:
        """Re-wire the whole farm: edges within each group live, every
        other edge cut (the reference's `part()` exactly)."""
        want = set()
        for grp in [list(g) for g in groups]:
            for a in grp:
                for b in grp:
                    want.add((a, b))
        for s in range(self.n):
            for d in range(self.n):
                if (s, d) in want:
                    self.connect(s, d)
                else:
                    self.disconnect(s, d)

    def heal(self) -> None:
        self.part(range(self.n))


class Proxy:
    """Make a remote server usable where clerks expect a server object:
    `proxy.method(*args)` → `call(addr, "method", *args)`.  RPCError
    propagates, which is exactly the failure clerks already handle."""

    def __init__(self, addr: str, timeout: float = 10.0):
        self._addr = addr
        self._timeout = timeout

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)

        def rpc(*args):
            return call(self._addr, name, *args, timeout=self._timeout)

        return rpc


def connect(addr: str, timeout: float = 10.0) -> Proxy:
    return Proxy(addr, timeout)
