"""netfault — deterministic byte-level wire fault injection (ISSUE 12).

durafault (utils/durafs.py) made the DISK a first-class fault domain by
owning the one durable-write seam; this module does the same for the
WIRE.  PR 10 moved the request hot path onto a versioned binary layout
decoded by a C++ epoll loop — which makes the byte stream itself a
thing that can fail, yet nemesis could only drop or delay whole calls.
A `WireFault` registered over a transport scope (a server socket path)
intercepts every framed send through that scope and injects faults at
the BYTE level:

    corrupt    flip bytes at deterministically-derived offsets — the
               receiver's decode state machine must reject the frame as
               a connection-scoped error (and the fe wire's CRC makes
               even payload-region flips detectable: corruption may
               never silently alter an op);
    truncate   send only the first ``frac`` of the framed bytes, then
               close — the peer sees a mid-frame EOF;
    split      re-chunk the send across many small syscalls (the frame
               arrives intact but never in one read) — exercises
               reassembly across syscall boundaries;
    coalesce   hold the frame and flush it glued to the FRONT of the
               next send on the same connection — two frames in one
               segment (the inverse re-chunking);
    stall      slow-loris: trickle the frame below a byte-rate floor —
               the receiver's per-conn read deadline is the defense;
    dup_frame  send the framed bytes TWICE, then close the connection
               (a duplicated delivery; the close keeps the sender's
               reply FIFO coherent, and the receiver's dup filter must
               absorb the byte-identical replay);
    reset      close the connection without sending anything — the op
               was never delivered.

Arming mirrors `DuraDisk` exactly: a FIFO of one-shot faults (`arm()`,
the nemesis `NetTarget`'s injection point — `net_fault {scope, kind,
frac}` events re-arm identically on replay) plus an optional seeded
per-send `NetFaultPlan` drawing at fixed per-kind rates.  Every
injection is recorded in `timeline` as `(send_index, kind, detail)` —
a pure function of (plan/armed sequence, send sizes), so the same seed
over the same send sequence replays the identical byte-level timeline.

The Python seam is `transport.FramedConn` (client→server bytes) and
`transport.Server`'s reply path (server→client bytes); native-ingest
connections are injectable through the C++ reply-path hook
(`rpcserver.cpp rpcsrv_netfault_*`, surfaced as
`NativeServer.set_netfault`).  Registration is by scope string (the
socket path): `register(addr, wf)` makes every *subsequently dialed*
`FramedConn` to that address consult `wf` — the harness registers
scopes before the clerks dial.
"""

from __future__ import annotations

import random
import threading
import time

from tpu6824.obs import metrics as _metrics

#: Closed fault-kind vocabulary, order is part of the C ABI (the native
#: reply-path hook receives the kind as an index into this tuple).
NET_FAULT_KINDS = ("corrupt", "truncate", "split", "coalesce", "stall",
                   "dup_frame", "reset")

#: stall pacing: bytes per trickle chunk and the inter-chunk sleep
#: ceiling.  The whole stall is bounded (chunks are sized so a frame
#: takes at most ~MAX_STALL_S) — the injector models a slow peer, not a
#: hung one; the receiver's read deadline is what unbounded slowness
#: would test, and that is covered by lowering the deadline in tests.
STALL_CHUNK = 64
MAX_STALL_S = 1.5

_M_INJECTED = _metrics.counter("netfault.injected")


class NetFaultPlan:
    """Seeded per-send fault sampler — `durafs.FaultPlan` for the wire.
    `rates` maps kind → probability; draws come off a private
    Random(seed) and ALWAYS consume exactly two draws per send, so
    fault placement is a pure function of the send index."""

    def __init__(self, seed: int, rates: dict[str, float] | None = None):
        bad = set(rates or ()) - set(NET_FAULT_KINDS)
        if bad:
            raise ValueError(f"unknown net fault kinds: {sorted(bad)}")
        self.seed = seed
        self.rates = dict(rates or {})
        self._rng = random.Random(seed)

    def draw(self) -> dict | None:
        u, frac = self._rng.random(), self._rng.random()
        acc = 0.0
        for kind in NET_FAULT_KINDS:
            acc += self.rates.get(kind, 0.0)
            if u < acc:
                return {"kind": kind, "frac": frac}
        return None


def corrupt_offsets(n: int, frac: float, index: int) -> list[int]:
    """The deterministic corrupt-placement function: byte offsets to
    flip in an n-byte framed send, derived purely from (n, frac,
    send index) — shared by tests asserting byte-level replay
    identity.  1–3 flips, anywhere in the frame (length prefix,
    header, payload: the decoder owes safety everywhere)."""
    rng = random.Random((index << 20) ^ int(frac * 1e6) ^ n)
    nflips = 1 + rng.randrange(3)
    return sorted({rng.randrange(n) for _ in range(nflips)})


class WireFault:
    """One injectable wire scope.  Thread-safe: many connections may
    send through one scope; the armed FIFO / plan draw / send index are
    taken under the lock, the (slow) byte-pushing itself is not."""

    def __init__(self, scope: str = "", plan: NetFaultPlan | None = None,
                 kinds: tuple = NET_FAULT_KINDS):
        bad = set(kinds) - set(NET_FAULT_KINDS)
        if bad:
            raise ValueError(f"unknown net fault kinds: {sorted(bad)}")
        self.scope = scope
        self.plan = plan
        self.kinds = tuple(kinds)
        self._mu = threading.Lock()
        self._armed: list[dict] = []      # FIFO of one-shot faults
        self.send_index = 0               # framed sends THROUGH the scope
        self.timeline: list[tuple] = []   # (send_index, kind, detail)
        self.counts: dict[str, int] = {}

    # ------------------------------------------------------------ arming

    def arm(self, kind: str, frac: float = 0.5) -> None:
        if kind not in NET_FAULT_KINDS:
            raise ValueError(f"unknown net fault kind {kind!r}")
        with self._mu:
            self._armed.append({"kind": kind, "frac": frac})

    def disarm(self) -> None:
        """Drop armed-but-unfired faults (the nemesis restore tail)."""
        with self._mu:
            self._armed.clear()

    # ----------------------------------------------------------- drawing

    def _next_fault(self, nbytes: int):
        """(send_index, fault|None) for the next framed send.  One
        timeline row per INJECTED fault; the index advances per send
        either way so placement replays identically."""
        with self._mu:
            idx = self.send_index
            self.send_index += 1
            fault = self._armed.pop(0) if self._armed else (
                self.plan.draw() if self.plan is not None else None)
            if fault is not None and fault["kind"] not in self.kinds:
                fault = None
            if fault is not None:
                kind = fault["kind"]
                self.counts[kind] = self.counts.get(kind, 0) + 1
                self.timeline.append((idx, kind,
                                      round(fault.get("frac", 0.5), 6),
                                      nbytes))
        if fault is not None:
            _M_INJECTED.inc(key=fault["kind"])
        return idx, fault

    # ---------------------------------------------------------- injection

    def send(self, sock, data: bytes, hold: bytearray | None = None,
             dup_literal: bool = True):
        """Push one fully-framed byte string (length prefix included)
        through `sock`, applying at most one injected fault.

        `hold` is the CONNECTION's coalesce buffer (the conn owns it;
        a scope is shared across conns).  `dup_literal=False` is the
        REPLY-direction mode: a literally-doubled reply would be
        undetectable by any client (the fe reply wire has no request
        ids — the next request would read the stale copy), so reply
        paths send once and tear instead; request-direction dups stay
        byte-identical replays the server dup filter must absorb.
        Returns the action applied: None (clean), or the fault kind.
        Raises ConnectionError after faults that tear the stream
        (truncate/dup_frame/reset) so the caller treats the connection
        as garbage — exactly the transport contract (the op may or may
        not have been delivered)."""
        if hold is not None and hold:
            # Flush held bytes glued to the front of this send — the
            # second half of a coalesce.
            data = bytes(hold) + data
            del hold[:]
        idx, fault = self._next_fault(len(data))
        if fault is None:
            sock.sendall(data)
            return None
        kind = fault["kind"]
        frac = fault.get("frac", 0.5)
        if kind == "corrupt":
            buf = bytearray(data)
            for off in corrupt_offsets(len(buf), frac, idx):
                buf[off] ^= 0xFF
            sock.sendall(bytes(buf))
            return kind
        if kind == "truncate":
            k = max(1, int(len(data) * min(max(frac, 0.01), 0.95)))
            try:
                sock.sendall(data[:k])
            finally:
                _close_quietly(sock)
            raise ConnectionError(
                f"netfault: truncated frame at byte {k}")
        if kind == "split":
            # Re-chunk across syscalls; frac picks the chunk size in
            # [1, len/2] so at least two segments always result.
            chunk = max(1, int(len(data) * min(max(frac, 0.02), 0.5)))
            for i in range(0, len(data), chunk):
                sock.sendall(data[i:i + chunk])
            return kind
        if kind == "coalesce":
            if hold is None:
                # No per-conn hold buffer (server reply path): degrade
                # to a split so the recorded injection still has a real
                # wire effect — the frame arrives re-chunked.
                chunk = max(1, int(len(data)
                                   * min(max(frac, 0.02), 0.5)))
                for i in range(0, len(data), chunk):
                    sock.sendall(data[i:i + chunk])
                return kind
            hold.extend(data)
            return kind
        if kind == "stall":
            delay = min(0.3, 0.02 + frac * 0.08)
            nchunks = max(2, min(len(data) // STALL_CHUNK + 1,
                                 int(MAX_STALL_S / delay)))
            chunk = max(STALL_CHUNK, len(data) // nchunks + 1)
            for i in range(0, len(data), chunk):
                sock.sendall(data[i:i + chunk])
                if i + chunk < len(data):
                    time.sleep(delay)
            return kind
        if kind == "dup_frame":
            try:
                sock.sendall(data)
                if dup_literal:
                    sock.sendall(data)
            finally:
                _close_quietly(sock)
            raise ConnectionError("netfault: frame duplicated, conn torn")
        if kind == "reset":
            _close_quietly(sock)
            raise ConnectionError("netfault: connection reset")
        raise AssertionError(kind)  # unreachable: closed vocabulary


def _close_quietly(sock) -> None:
    try:
        sock.close()
    except OSError:
        pass


# ------------------------------------------------------------- registry
#
# Scope registry: the harness registers a WireFault per socket path
# BEFORE clerks dial; FramedConn consults it at dial time, the servers
# via set_netfault().  Process-local, test-scoped — reset() between
# tests like transport.reset_pool().

_reg_mu = threading.Lock()
_registry: dict[str, WireFault] = {}


def register(scope: str, wf: WireFault) -> WireFault:
    with _reg_mu:
        _registry[scope] = wf
    return wf


def unregister(scope: str) -> None:
    with _reg_mu:
        _registry.pop(scope, None)


def for_addr(addr: str) -> WireFault | None:
    with _reg_mu:
        return _registry.get(addr)


def reset() -> None:
    """Drop every registered scope (test isolation helper)."""
    with _reg_mu:
        _registry.clear()
