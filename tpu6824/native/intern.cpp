// Refcounted value-intern store — native runtime component.
//
// The framework's host-side "allocator": consensus values never touch the
// device (the kernel agrees on int32 ids, SURVEY §7); every Start() interns
// its payload here, and the Done/Min window GC drops references when slots
// are recycled (the doMemShrink/TestForgetMem semantics of the reference,
// paxos/paxos.go:362-378, paxos/test_test.go:371-454).  This C++ core owns
// the dedup index, refcounts and free-list under one mutex; the Python side
// (intern.py) keeps only an id→value list for O(1) lookup without
// re-serialization.
//
// C ABI for ctypes.  Build: g++ -O2 -std=c++17 -shared -fPIC -o
// libintern6824.so intern.cpp  (driven by intern.py).

#include <cstdint>
#include <mutex>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

struct Store {
  std::mutex mu;
  std::unordered_map<std::string, int32_t> by_key;
  std::vector<std::string> keys;    // id → serialized payload key
  std::vector<int64_t> refs;        // id → refcount (0 = slot free)
  std::vector<int32_t> free_ids;
  int64_t live_bytes = 0;
};

}  // namespace

extern "C" {

void* intern_new() { return new Store(); }

void intern_destroy(void* h) { delete static_cast<Store*>(h); }

// Intern `key` and take one reference.  Returns the id; *is_new is 1 iff the
// id was (re)allocated by this call, telling the caller to (re)bind its
// id→value mirror.
int32_t intern_put(void* h, const char* key, int64_t klen, int32_t* is_new) {
  auto* s = static_cast<Store*>(h);
  std::string k(key, static_cast<size_t>(klen));
  std::lock_guard<std::mutex> g(s->mu);
  auto it = s->by_key.find(k);
  if (it != s->by_key.end()) {
    *is_new = 0;
    s->refs[it->second] += 1;
    return it->second;
  }
  int32_t vid;
  if (!s->free_ids.empty()) {
    vid = s->free_ids.back();
    s->free_ids.pop_back();
    s->keys[vid] = std::move(k);
    s->refs[vid] = 1;
  } else {
    vid = static_cast<int32_t>(s->keys.size());
    s->keys.push_back(std::move(k));
    s->refs.push_back(1);
  }
  s->by_key.emplace(s->keys[vid], vid);
  s->live_bytes += klen;
  *is_new = 1;
  return vid;
}

void intern_incref(void* h, int32_t vid) {
  auto* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  s->refs[vid] += 1;
}

// Drops one reference; returns 1 iff the payload was freed (caller clears
// its id→value mirror), 0 otherwise.
int32_t intern_decref(void* h, int32_t vid) {
  auto* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  if (s->refs[vid] <= 0) return 0;  // already free — tolerate double-decref
  if (--s->refs[vid] > 0) return 0;
  s->by_key.erase(s->keys[vid]);
  s->live_bytes -= static_cast<int64_t>(s->keys[vid].size());
  s->keys[vid].clear();
  s->keys[vid].shrink_to_fit();
  s->free_ids.push_back(vid);
  return 1;
}

int64_t intern_nlive(void* h) {
  auto* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  return static_cast<int64_t>(s->keys.size() - s->free_ids.size());
}

int64_t intern_bytes(void* h) {
  auto* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  return s->live_bytes;
}

int64_t intern_refcount(void* h, int32_t vid) {
  auto* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  return s->refs[vid];
}

}  // extern "C"
