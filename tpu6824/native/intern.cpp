// Refcounted value-intern store — native runtime component.
//
// The framework's host-side "allocator": consensus values never touch the
// device (the kernel agrees on int32 ids, SURVEY §7); every Start() interns
// its payload here, and the Done/Min window GC drops references when slots
// are recycled (the doMemShrink/TestForgetMem semantics of the reference,
// paxos/paxos.go:362-378, paxos/test_test.go:371-454).
//
// The store itself lives in intern_core.h (ISSUE 11): the epoll server
// (rpcserver.cpp) compiles the same core so its loop thread can intern
// clerk keys/values with no GIL; this file is the C ABI the Python
// NativeIntern mirror loads.  New in the shared core: an id-LOOKUP surface
// (`intern_get_bytes`) so a caller can recover the payload bytes from an
// id alone — the Python side of the native-ingest path materializes
// key/value strings lazily through it instead of keeping every payload
// mirrored eagerly.
//
// C ABI for ctypes.  Build: g++ -O2 -std=c++17 -shared -fPIC -o
// libintern6824.so intern.cpp  (driven by intern.py).

#include <cstdint>

#include "intern_core.h"

using intern_core::Store;

extern "C" {

void* intern_new() { return new Store(); }

void intern_destroy(void* h) { delete static_cast<Store*>(h); }

// Intern `key` and take one reference.  Returns the id; *is_new is 1 iff the
// id was (re)allocated by this call, telling the caller to (re)bind its
// id→value mirror.
int32_t intern_put(void* h, const char* key, int64_t klen, int32_t* is_new) {
  return intern_core::store_put(static_cast<Store*>(h), key, klen, is_new);
}

void intern_incref(void* h, int32_t vid) {
  intern_core::store_incref(static_cast<Store*>(h), vid);
}

// Drops one reference; returns 1 iff the payload was freed (caller clears
// its id→value mirror), 0 otherwise.
int32_t intern_decref(void* h, int32_t vid) {
  return intern_core::store_decref(static_cast<Store*>(h), vid);
}

// Copy a live id's payload bytes into `out` (cap bytes); returns the
// payload length (> cap: nothing copied, retry bigger), -1 if free.
int64_t intern_get_bytes(void* h, int32_t vid, char* out, int64_t cap) {
  return intern_core::store_get_copy(static_cast<Store*>(h), vid, out, cap);
}

int64_t intern_nlive(void* h) {
  return intern_core::store_nlive(static_cast<Store*>(h));
}

int64_t intern_bytes(void* h) {
  return intern_core::store_bytes(static_cast<Store*>(h));
}

int64_t intern_refcount(void* h, int32_t vid) {
  return intern_core::store_refcount(static_cast<Store*>(h), vid);
}

}  // extern "C"
