// Shared refcounted byte-intern store — the native runtime's "allocator"
// core, extracted from intern.cpp (ISSUE 11) so the epoll server
// (rpcserver.cpp) can intern clerk keys/values ON ITS LOOP THREAD with no
// GIL and no cross-library calls: both .cpp files compile this header into
// their own .so, and each operates only on stores it created itself.
//
// The store maps byte strings to dense int32 ids with refcounts and a
// free-list; payload bytes live in `keys` (ids index it), `by_key` is the
// dedup index.  All operations take the store's own mutex — callers never
// need external locking, and the epoll loop thread and Python (via ctypes,
// which drops the GIL around C calls) interleave safely.
//
// Pointer-stability caveat: `keys` is a std::vector<std::string>, so
// growth MOVES the string objects (and SSO payloads with them).  Readers
// therefore COPY bytes out under the mutex (store_get_copy) instead of
// returning interior pointers.

#pragma once

#include <cstdint>
#include <cstring>
#include <mutex>
#include <string>
#include <unordered_map>
#include <vector>

namespace intern_core {

struct Store {
  std::mutex mu;
  std::unordered_map<std::string, int32_t> by_key;
  std::vector<std::string> keys;    // id → payload bytes
  std::vector<int64_t> refs;        // id → refcount (0 = slot free)
  std::vector<int32_t> free_ids;
  int64_t live_bytes = 0;
};

// Intern `data` and take one reference.  *is_new is 1 iff the id was
// (re)allocated by this call (telling a Python caller to (re)bind its
// id→value mirror).
inline int32_t store_put(Store* s, const char* data, int64_t len,
                         int32_t* is_new) {
  std::string k(data, static_cast<size_t>(len));
  std::lock_guard<std::mutex> g(s->mu);
  auto it = s->by_key.find(k);
  if (it != s->by_key.end()) {
    if (is_new) *is_new = 0;
    s->refs[it->second] += 1;
    return it->second;
  }
  int32_t vid;
  if (!s->free_ids.empty()) {
    vid = s->free_ids.back();
    s->free_ids.pop_back();
    s->keys[vid] = std::move(k);
    s->refs[vid] = 1;
  } else {
    vid = static_cast<int32_t>(s->keys.size());
    s->keys.push_back(std::move(k));
    s->refs.push_back(1);
  }
  s->by_key.emplace(s->keys[vid], vid);
  s->live_bytes += len;
  if (is_new) *is_new = 1;
  return vid;
}

inline void store_incref(Store* s, int32_t vid) {
  std::lock_guard<std::mutex> g(s->mu);
  s->refs[vid] += 1;
}

// Drops one reference; returns 1 iff the payload was freed (caller clears
// its id→value mirror), 0 otherwise.  Double-decref is tolerated.
inline int32_t store_decref(Store* s, int32_t vid) {
  std::lock_guard<std::mutex> g(s->mu);
  if (vid < 0 || size_t(vid) >= s->refs.size() || s->refs[vid] <= 0)
    return 0;
  if (--s->refs[vid] > 0) return 0;
  s->live_bytes -= static_cast<int64_t>(s->keys[vid].size());
  s->by_key.erase(s->keys[vid]);
  s->keys[vid].clear();
  s->keys[vid].shrink_to_fit();
  s->free_ids.push_back(vid);
  return 1;
}

// Copy the payload bytes for a LIVE id into `out` (cap bytes available);
// returns the payload length, or -1 for a free/unknown id.  A return
// value > cap means "buffer too small, call again with a bigger one" —
// nothing was copied.  This is the id-LOOKUP surface the native ingest
// path and the Python mirror share.
inline int64_t store_get_copy(Store* s, int32_t vid, char* out,
                              int64_t cap) {
  std::lock_guard<std::mutex> g(s->mu);
  if (vid < 0 || size_t(vid) >= s->refs.size() || s->refs[vid] <= 0)
    return -1;
  const std::string& k = s->keys[vid];
  int64_t n = static_cast<int64_t>(k.size());
  if (n <= cap) memcpy(out, k.data(), k.size());
  return n;
}

inline int64_t store_nlive(Store* s) {
  std::lock_guard<std::mutex> g(s->mu);
  return static_cast<int64_t>(s->keys.size() - s->free_ids.size());
}

inline int64_t store_bytes(Store* s) {
  std::lock_guard<std::mutex> g(s->mu);
  return s->live_bytes;
}

inline int64_t store_refcount(Store* s, int32_t vid) {
  std::lock_guard<std::mutex> g(s->mu);
  if (vid < 0 || size_t(vid) >= s->refs.size()) return 0;
  return s->refs[vid];
}

}  // namespace intern_core
