// Thread-safe fixed-capacity LRU cache — native runtime component.
//
// Capability parity with the reference's lru package (groupcache-derived,
// lru/lru.go:17-186): Put/Get/Peek/Contains/ContainsOrAdd/Remove/Keys/Len,
// where Get refreshes recency and Peek does not.  The reference implements it
// in Go with container/list; this is the C++ equivalent (intrusive doubly-
// linked list + hash map, one mutex per cache) exposed through a C ABI for
// ctypes.
//
// Build: g++ -O2 -shared -fPIC -o liblru6824.so lru.cpp  (driven by lru.py)

#include <cstdint>
#include <cstring>
#include <list>
#include <mutex>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

struct Entry {
  std::string key;
  std::string val;
};

struct Cache {
  explicit Cache(size_t cap) : capacity(cap) {}
  size_t capacity;
  std::mutex mu;
  std::list<Entry> order;  // front = most recent
  std::unordered_map<std::string, std::list<Entry>::iterator> index;

  void touch(std::list<Entry>::iterator it) { order.splice(order.begin(), order, it); }

  void evict_to_capacity() {
    while (index.size() > capacity && !order.empty()) {
      index.erase(order.back().key);
      order.pop_back();
    }
  }
};

}  // namespace

extern "C" {

void* lru_new(uint64_t capacity) { return new Cache(capacity ? capacity : 1); }

void lru_free(void* h) { delete static_cast<Cache*>(h); }

// Returns 1 if the put evicted nothing & key was new, 0 if it replaced or
// evicted (parity with lru.go Put's eviction report).
int32_t lru_put(void* h, const char* key, int32_t klen, const char* val,
                int32_t vlen) {
  auto* c = static_cast<Cache*>(h);
  std::string k(key, klen), v(val, vlen);
  std::lock_guard<std::mutex> g(c->mu);
  auto it = c->index.find(k);
  if (it != c->index.end()) {
    it->second->val = std::move(v);
    c->touch(it->second);
    return 0;
  }
  c->order.push_front(Entry{k, std::move(v)});
  c->index[k] = c->order.begin();
  size_t before = c->index.size();
  c->evict_to_capacity();
  return c->index.size() == before ? 1 : 0;
}

// Returns value length (and copies min(vlen, buflen) bytes into buf), or -1
// if absent.  promote != 0 → Get semantics (refresh recency); 0 → Peek.
int32_t lru_get(void* h, const char* key, int32_t klen, char* buf,
                int32_t buflen, int32_t promote) {
  auto* c = static_cast<Cache*>(h);
  std::string k(key, klen);
  std::lock_guard<std::mutex> g(c->mu);
  auto it = c->index.find(k);
  if (it == c->index.end()) return -1;
  if (promote) c->touch(it->second);
  const std::string& v = it->second->val;
  int32_t n = static_cast<int32_t>(v.size());
  if (buf && buflen > 0) std::memcpy(buf, v.data(), std::min<int32_t>(n, buflen));
  return n;
}

int32_t lru_contains(void* h, const char* key, int32_t klen) {
  auto* c = static_cast<Cache*>(h);
  std::string k(key, klen);
  std::lock_guard<std::mutex> g(c->mu);
  return c->index.count(k) ? 1 : 0;
}

// Returns 1 if key was already present (no change), else adds and returns 0.
int32_t lru_contains_or_add(void* h, const char* key, int32_t klen,
                            const char* val, int32_t vlen) {
  auto* c = static_cast<Cache*>(h);
  std::string k(key, klen);
  std::lock_guard<std::mutex> g(c->mu);
  if (c->index.count(k)) return 1;
  c->order.push_front(Entry{k, std::string(val, vlen)});
  c->index[k] = c->order.begin();
  c->evict_to_capacity();
  return 0;
}

int32_t lru_remove(void* h, const char* key, int32_t klen) {
  auto* c = static_cast<Cache*>(h);
  std::string k(key, klen);
  std::lock_guard<std::mutex> g(c->mu);
  auto it = c->index.find(k);
  if (it == c->index.end()) return 0;
  c->order.erase(it->second);
  c->index.erase(it);
  return 1;
}

uint64_t lru_len(void* h) {
  auto* c = static_cast<Cache*>(h);
  std::lock_guard<std::mutex> g(c->mu);
  return c->index.size();
}

// Copies up to `max` keys (most-recent first) as len-prefixed records into
// buf; returns bytes written, or the required size if buf is null.
int64_t lru_keys(void* h, char* buf, int64_t buflen) {
  auto* c = static_cast<Cache*>(h);
  std::lock_guard<std::mutex> g(c->mu);
  int64_t need = 0;
  for (const auto& e : c->order) need += 4 + static_cast<int64_t>(e.key.size());
  if (!buf) return need;
  if (buflen < need) return -1;
  char* p = buf;
  for (const auto& e : c->order) {
    int32_t n = static_cast<int32_t>(e.key.size());
    std::memcpy(p, &n, 4);
    p += 4;
    std::memcpy(p, e.key.data(), n);
    p += n;
  }
  return need;
}

}  // extern "C"
