from tpu6824.native.lru import LRUCache  # noqa: F401
