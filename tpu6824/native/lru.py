"""ctypes bindings for the native LRU cache (lru.cpp), with a pure-Python
fallback when no C++ toolchain is available.

Capability parity with the reference's lru package (`lru/lru.go:17-186`):
Put/Get/Peek/Contains/ContainsOrAdd/Remove/Keys/Len; Get promotes recency,
Peek does not.  The shared library is built on first import into
`<repo>/build/` and cached."""

from __future__ import annotations

import ctypes
import os
import threading
from collections import OrderedDict

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "lru.cpp")

_lib = None
_lib_lock = threading.Lock()


def _load():
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        from tpu6824.native import build

        lib = build.load("liblru6824.so", _SRC)
        if lib is None:
            _lib = False  # toolchain unavailable -> python fallback
            return _lib
        lib.lru_new.restype = ctypes.c_void_p
        lib.lru_new.argtypes = [ctypes.c_uint64]
        lib.lru_free.argtypes = [ctypes.c_void_p]
        lib.lru_put.restype = ctypes.c_int32
        lib.lru_put.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                ctypes.c_int32, ctypes.c_char_p, ctypes.c_int32]
        lib.lru_get.restype = ctypes.c_int32
        lib.lru_get.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                ctypes.c_int32, ctypes.c_char_p,
                                ctypes.c_int32, ctypes.c_int32]
        lib.lru_contains.restype = ctypes.c_int32
        lib.lru_contains.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int32]
        lib.lru_contains_or_add.restype = ctypes.c_int32
        lib.lru_contains_or_add.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int32,
            ctypes.c_char_p, ctypes.c_int32,
        ]
        lib.lru_remove.restype = ctypes.c_int32
        lib.lru_remove.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int32]
        lib.lru_len.restype = ctypes.c_uint64
        lib.lru_len.argtypes = [ctypes.c_void_p]
        lib.lru_keys.restype = ctypes.c_int64
        lib.lru_keys.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64]
        _lib = lib
        return _lib


class LRUCache:
    """str→str LRU with the reference lru package's API surface."""

    def __init__(self, capacity: int):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = capacity
        lib = _load()
        if lib:
            self._h = lib.lru_new(capacity)
            self._lib = lib
            self._py = None
        else:  # pragma: no cover — fallback path
            self._h = None
            self._lib = None
            self._py = OrderedDict()
            self._mu = threading.Lock()

    @property
    def native(self) -> bool:
        return self._lib is not None and self._lib is not False

    def __del__(self):
        if getattr(self, "_lib", None) and self._h:
            self._lib.lru_free(self._h)
            self._h = None

    # -------------------------------------------------------------- API

    def put(self, key: str, value: str):
        if self._py is not None:
            with self._mu:
                self._py.pop(key, None)
                self._py[key] = value
                while len(self._py) > self.capacity:
                    self._py.popitem(last=False)
            return
        k, v = key.encode(), value.encode()
        self._lib.lru_put(self._h, k, len(k), v, len(v))

    def _get(self, key: str, promote: int):
        if self._py is not None:
            with self._mu:
                if key not in self._py:
                    return None
                v = self._py[key]
                if promote:
                    self._py.move_to_end(key)
                return v
        k = key.encode()
        # Single locked native call per attempt: lru_get copies min(n, buflen)
        # bytes and returns the value's true length, so a value that grew
        # under a concurrent put just triggers a retry — never a torn read.
        cap = 256
        while True:
            buf = ctypes.create_string_buffer(cap)
            n = self._lib.lru_get(self._h, k, len(k), buf, cap, promote)
            if n < 0:
                return None
            if n <= cap:
                return buf.raw[:n].decode()
            cap = n

    def get(self, key: str):
        """Promotes recency (lru.go Get :92-101)."""
        return self._get(key, 1)

    def peek(self, key: str):
        """No recency change (lru.go Peek :104-113)."""
        return self._get(key, 0)

    def contains(self, key: str) -> bool:
        if self._py is not None:
            with self._mu:
                return key in self._py
        k = key.encode()
        return bool(self._lib.lru_contains(self._h, k, len(k)))

    def contains_or_add(self, key: str, value: str) -> bool:
        """True if already present; else adds (lru.go ContainsOrAdd)."""
        if self._py is not None:
            with self._mu:
                if key in self._py:
                    return True
                self._py[key] = value
                while len(self._py) > self.capacity:
                    self._py.popitem(last=False)
                return False
        k, v = key.encode(), value.encode()
        return bool(self._lib.lru_contains_or_add(self._h, k, len(k), v, len(v)))

    def remove(self, key: str) -> bool:
        if self._py is not None:
            with self._mu:
                return self._py.pop(key, None) is not None
        k = key.encode()
        return bool(self._lib.lru_remove(self._h, k, len(k)))

    def keys(self) -> list[str]:
        """Most-recent first (lru.go Keys)."""
        if self._py is not None:
            with self._mu:
                return list(reversed(self._py.keys()))
        # lru_keys returns -1 if the cache outgrew the buffer between the
        # size query and the copy; headroom + retry keeps the read atomic.
        need = int(self._lib.lru_keys(self._h, None, 0))
        while True:
            cap = need + 1024
            buf = ctypes.create_string_buffer(cap)
            wrote = int(self._lib.lru_keys(self._h, buf, cap))
            if wrote >= 0:
                break
            need = int(self._lib.lru_keys(self._h, None, 0))
        out, off = [], 0
        raw = buf.raw[:wrote]
        while off < len(raw):
            n = int.from_bytes(raw[off:off + 4], "little")
            off += 4
            out.append(raw[off:off + n].decode())
            off += n
        return out

    def __len__(self) -> int:
        if self._py is not None:
            with self._mu:
                return len(self._py)
        return int(self._lib.lru_len(self._h))
