// Native L0 transport server — epoll event loop for the Unix-socket RPC
// endpoints (the runtime under tpu6824/rpc/native_server.py).
//
// The reference's per-server accept loop is its runtime kernel: it owns the
// listening socket, injects faults (drop 10% of connections unprocessed,
// serve-but-discard 20% of replies via SHUT_WR), and counts RPCs
// (paxos/paxos.go:524-552).  This is that loop as a native event loop:
// one epoll thread per server handles accept/read/write for every
// connection; request payloads are handed to the embedding runtime through
// a callback; replies come back on ANY thread via rpcsrv_reply (eventfd
// wakeup), so slow handlers never stall the loop.
//
// Framing matches tpu6824/rpc/transport.py: 4-byte big-endian length prefix,
// opaque payload (the codec lives above).  Semantics mirrored from the
// Python Server: connections are PERSISTENT (the pooled client default —
// many requests per connection; a dial-per-call client simply sends one),
// rpc_count increments per served request, and the fault coins are drawn
// per REQUEST with every injected fault tearing the connection down: the
// request-drop path discards the frame unprocessed, the reply-discard path
// executes the handler then SHUT_WR so the client sees a dead connection
// after the op ran — the executed-but-unacked case the at-most-once
// machinery upstairs is tested against.
//
// NATIVE INGEST (ISSUE 11): with ingest enabled, versioned fe_batch
// frames (fewire.h — the little-endian layout shared with rpc/wire.py)
// are decoded ON THE LOOP THREAD straight into per-frame int64/int32
// columnar buffers — op kind, cid, cseq, key-id, value-id — with key and
// value bytes interned into native stores (intern_core.h), all without
// the GIL.  The Python engine polls ready frames (one memcpy per column
// into its own numpy buffers), hands the arrays to submit_columnar, and
// the reply path mirrors it: the driver's notify sweep pushes (tag, err,
// value-id) triples into the native reply ring, and THIS loop serializes
// the completed frame's reply bytes and flushes them — steady-state
// operation builds no per-op Python objects on either direction.
//
// C ABI only; loaded via ctypes (no pybind11 in this image).

#include <algorithm>
#include <atomic>
#include <cerrno>
#include <cstdint>
#include <cstring>
#include <deque>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

#include <chrono>

#include <fcntl.h>
#include <sys/epoll.h>
#include <sys/eventfd.h>
#include <sys/socket.h>
#include <sys/un.h>
#include <unistd.h>

#include "fewire.h"
#include "intern_core.h"

namespace {

constexpr size_t kMaxFrame = 64ull << 20;
constexpr double kReqDrop = 0.10;  // paxos/paxos.go:528-531
constexpr double kRepDrop = 0.20;  // paxos/paxos.go:535-538
constexpr int64_t kConnTimeoutMs = 30'000;  // transport.py settimeout(30.0)

// netfault (ISSUE 12): reply-path byte-fault kinds, indices matching
// rpc/netfault.py NET_FAULT_KINDS.  coalesce has no event-loop meaning
// on a deferred-reply server (replies already batch per drain) and is
// applied as split — the frame still arrives re-chunked.
constexpr int kNfCorrupt = 0, kNfTruncate = 1, kNfSplit = 2,
              kNfCoalesce = 3, kNfStall = 4, kNfDup = 5, kNfReset = 6;
constexpr int kNumNetFaults = 7;

int64_t now_ms() {
  return std::chrono::duration_cast<std::chrono::milliseconds>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

using Callback = void (*)(uint64_t conn_id, const uint8_t* data,
                          int64_t len);

struct Conn {
  int fd = -1;
  bool discard_reply = false;  // fault drawn for the CURRENT request
  bool handed_off = false;     // one request in flight per connection
  bool want_write = false;
  int64_t deadline_ms = 0;   // absolute steady-clock ms; 30s per I/O phase
  // netfault reply-path state (ISSUE 12): injected write shaping.
  bool close_after_write = false;  // truncate/dup: tear once flushed
  size_t write_cap = 0;            // split/stall: max bytes per write()
  int64_t pace_ms = 0;             // stall: min gap between writes
  int64_t next_write_ms = 0;
  std::vector<uint8_t> rbuf;
  std::vector<uint8_t> wbuf;
  size_t woff = 0;
};

struct Reply {
  uint64_t conn_id;
  std::vector<uint8_t> data;
  // opscope (ISSUE 15): the reply-ring completion instant for fe
  // frames (0 for everything else) — the flush stage measures from
  // here to the loop's serialize/flush of the frame.
  int64_t t_ns = 0;
};

// One ingested fe_batch frame: columnar op buffers (filled by the loop
// thread, copied out once by the Python engine) plus the reply-side state
// (err/rep_val per slot) the push path completes against.  err 255 =
// slot unanswered.
struct FeFrame {
  uint64_t id = 0;
  uint64_t conn_id = 0;
  uint32_t nops = 0;
  uint32_t remaining = 0;
  bool has_tc = false;
  bool want_crc = false;     // request carried kFlagCrc: echo it back
  uint32_t deadline_ms = 0;  // propagated clerk op budget (0 = none)
  // opscope (ISSUE 15): frame-parse instant, stamped on the loop
  // thread (steady clock ns == time.monotonic_ns) — rides the poll1
  // hdr as the ingest-ring ts column's per-frame value, the origin of
  // every op's stage waterfall.
  int64_t ts_ns = 0;
  uint64_t tc[2] = {0, 0};
  std::vector<int32_t> kind, key_id, val_id;
  std::vector<int64_t> cid, cseq;
  std::vector<uint8_t> err;       // reply err code per slot
  std::vector<uint8_t> answered;  // 1 once a push landed on the slot
  std::vector<int32_t> rep_val;   // reply value id (vals store), -1 = ""
};

// Per-server native-ingest state.  `mu` guards the frame table and the
// fresh/done queues; the intern stores carry their own mutexes (the loop
// thread and Python threads interleave on them freely).
struct Ingest {
  std::mutex mu;
  intern_core::Store keys, vals;
  std::unordered_map<uint64_t, FeFrame*> frames;
  std::deque<uint64_t> fresh;  // ingested, not yet polled by the engine
  std::deque<uint64_t> done;   // replied/failed, awaiting engine reap
  uint64_t next_frame = 1;
  int efd = -1;  // engine wakeup eventfd (loop writes, engine selects)
  int64_t inflight_ops = 0;
  int64_t max_ops = 1 << 16;  // backpressure: beyond this, frames bounce
  // native_ingest counters (mirrored into the Python metrics registry).
  std::atomic<int64_t> c_frames{0}, c_ops{0}, c_bytes{0}, c_full{0};
  std::atomic<int64_t> c_done_ops{0};  // ops answered (reply or fail)
  // opscope flush-stage histogram (ISSUE 15): log2 µs buckets of the
  // reply-ring-push → serialize/flush interval, per completed frame.
  // Cumulative; the Python engine mirrors deltas once per pass
  // (rpcsrv_opscope_flush).  Aggregate-initialized to zero.
  std::atomic<int64_t> fl_buckets[64] = {};
  std::atomic<int64_t> fl_count{0}, fl_sum_us{0};
};

struct Server {
  int lfd = -1, epfd = -1, evfd = -1;
  std::string path;
  std::atomic<bool> dead{false};
  std::atomic<bool> unreliable{false};
  std::atomic<int64_t> rpc_count{0};
  // Malformed/oversized input rejected at the decode state machine —
  // connection-scoped, counted, never a crash (mirrored into the
  // registry as rpc.wire.rejected by the Python wrapper).
  std::atomic<int64_t> wire_rejected{0};
  // Per-conn I/O-phase deadline (ms); settable so slow-loris defense
  // tests run in finite time.
  std::atomic<int64_t> io_deadline_ms{kConnTimeoutMs};
  uint64_t rng;
  Callback cb;
  std::thread loop;
  std::mutex mu;  // guards pending
  std::deque<Reply> pending;
  std::unordered_map<uint64_t, Conn> conns;
  uint64_t next_id = 1;
  std::atomic<Ingest*> ingest{nullptr};  // set once by rpcsrv_ingest_enable
  // netfault reply-path injector (ISSUE 12): one-shot FIFO + optional
  // seeded per-reply plan, drawn in drain_replies under nf_mu.
  std::mutex nf_mu;
  std::deque<std::pair<int, double>> nf_armed;  // (kind, frac)
  bool nf_plan = false;
  uint64_t nf_rng = 1;
  double nf_rates[kNumNetFaults] = {0};
  uint64_t nf_index = 0;                  // reply send index
  std::atomic<int64_t> nf_injected{0};
  std::atomic<int> paced{0};              // conns mid-stall (loop tick)
};

double next_unit(uint64_t& s) {  // xorshift64*, uniform in [0,1)
  s ^= s >> 12;
  s ^= s << 25;
  s ^= s >> 27;
  return double((s * 2685821657736338717ull) >> 11) / double(1ull << 53);
}

void set_nonblock(int fd) {
  int fl = fcntl(fd, F_GETFL, 0);
  fcntl(fd, F_SETFL, fl | O_NONBLOCK);
}

void epoll_mod(Server* s, uint64_t id, Conn& c) {
  epoll_event ev{};
  // A paced (netfault-stalled) reply must NOT arm EPOLLOUT: the socket
  // stays writable, so level-triggered EPOLLOUT would hot-spin the
  // loop; the loop's timeout tick resumes the trickle instead.
  ev.events = (c.handed_off ? 0u : unsigned(EPOLLIN)) |
              (c.want_write && !c.pace_ms ? unsigned(EPOLLOUT) : 0u);
  ev.data.u64 = id;
  epoll_ctl(s->epfd, EPOLL_CTL_MOD, c.fd, &ev);
}

void close_conn(Server* s, uint64_t id) {
  auto it = s->conns.find(id);
  if (it == s->conns.end()) return;
  if (it->second.pace_ms)
    s->paced.fetch_sub(1, std::memory_order_relaxed);
  epoll_ctl(s->epfd, EPOLL_CTL_DEL, it->second.fd, nullptr);
  close(it->second.fd);
  s->conns.erase(it);
}

void handle_accept(Server* s) {
  for (;;) {
    int fd = accept4(s->lfd, nullptr, nullptr, SOCK_NONBLOCK);
    if (fd < 0) return;
    uint64_t id = s->next_id++;
    Conn& c = s->conns[id];
    c.fd = fd;
    c.deadline_ms = now_ms() + s->io_deadline_ms.load(std::memory_order_relaxed);
    epoll_event ev{};
    ev.events = EPOLLIN;
    ev.data.u64 = id;
    epoll_ctl(s->epfd, EPOLL_CTL_ADD, fd, &ev);
  }
}

// Thread-safe reply enqueue: the loop's pending deque + eventfd wake —
// usable from the loop thread itself (immediate ingest errors) and from
// any Python thread (the push path's completed frames).
void enqueue_reply(Server* s, uint64_t conn_id, std::vector<uint8_t>&& data,
                   int64_t t_ns = 0) {
  {
    std::lock_guard<std::mutex> g(s->mu);
    s->pending.push_back(Reply{conn_id, std::move(data), t_ns});
  }
  uint64_t one = 1;
  ssize_t ignored = write(s->evfd, &one, 8);
  (void)ignored;
}

std::vector<uint8_t> fe_error_bytes(const char* msg) {
  size_t mlen = strlen(msg);
  std::vector<uint8_t> out(8 + mlen);
  out[0] = 'F';
  out[1] = 'E';
  out[2] = 'E';
  out[3] = fewire::kFeVersion;
  fewire::store<uint32_t>(out.data() + 4, uint32_t(mlen));
  memcpy(out.data() + 8, msg, mlen);
  return out;
}

void ingest_wake_engine(Ingest* ing) {
  uint64_t one = 1;
  ssize_t ignored = write(ing->efd, &one, 8);
  (void)ignored;
}

// Assemble the completed frame's FER reply (err + value bytes per slot,
// values read out of the native store), hand it to the loop, and retire
// the frame to the reap queue.  Caller holds ing->mu.
void fe_complete_locked(Server* s, Ingest* ing, FeFrame* f) {
  // opscope flush stage starts here: the last reply-ring push just
  // completed the frame; everything from this instant to the loop's
  // socket flush is native serialize/flush cost.
  int64_t t_push = fewire::mono_ns();
  std::vector<int64_t> vlens(f->nops, 0);
  size_t total = fewire::kHdrSize + (f->want_crc ? 4 : 0);
  {
    std::lock_guard<std::mutex> g(ing->vals.mu);
    for (uint32_t i = 0; i < f->nops; i++) {
      int32_t vid = f->rep_val[i];
      if (vid >= 0 && size_t(vid) < ing->vals.refs.size() &&
          ing->vals.refs[vid] > 0)
        vlens[i] = int64_t(ing->vals.keys[vid].size());
      total += 5 + size_t(vlens[i]);
    }
  }
  if (total > kMaxFrame) {
    // Reply past the transport frame cap (e.g. a batch of huge gets):
    // answer with an explicit error instead of a frame the client's
    // receive cap would reject — a silent oversized reply is a retry
    // livelock (the dup filter re-serves it forever).
    for (uint32_t i = 0; i < f->nops; i++)
      if (f->rep_val[i] >= 0)
        intern_core::store_decref(&ing->vals, f->rep_val[i]);
    enqueue_reply(s, f->conn_id,
                  fe_error_bytes("reply too large for one fe frame"),
                  t_push);
    ing->done.push_back(f->id);
    ing->inflight_ops -= f->nops;
    ing->c_done_ops.fetch_add(f->nops, std::memory_order_relaxed);
    return;
  }
  std::vector<uint8_t> out(total);
  out[0] = 'F';
  out[1] = 'E';
  out[2] = 'R';
  out[3] = fewire::kFeVersion;
  fewire::store<uint16_t>(out.data() + 4,
                          f->want_crc ? fewire::kFlagCrc : 0);
  fewire::store<uint16_t>(out.data() + 6, uint16_t(f->nops));
  size_t off = fewire::kHdrSize;
  size_t crc_off = 0;
  if (f->want_crc) {  // 4 reserved bytes, stamped after serialization
    crc_off = off;
    fewire::store<uint32_t>(out.data() + off, 0);
    off += 4;
  }
  {
    std::lock_guard<std::mutex> g(ing->vals.mu);
    for (uint32_t i = 0; i < f->nops; i++) {
      out[off] = f->err[i];
      fewire::store<uint32_t>(out.data() + off + 1, uint32_t(vlens[i]));
      off += 5;
      if (vlens[i] > 0) {
        memcpy(out.data() + off, ing->vals.keys[f->rep_val[i]].data(),
               size_t(vlens[i]));
        off += size_t(vlens[i]);
      }
    }
  }
  for (uint32_t i = 0; i < f->nops; i++)
    if (f->rep_val[i] >= 0)
      intern_core::store_decref(&ing->vals, f->rep_val[i]);
  if (f->want_crc) {
    uint32_t c = fewire::crc32(out.data(), crc_off);
    c = fewire::crc32(out.data() + crc_off + 4, out.size() - crc_off - 4,
                      c);
    fewire::store<uint32_t>(out.data() + crc_off, c);
  }
  enqueue_reply(s, f->conn_id, std::move(out), t_push);
  ing->done.push_back(f->id);
  ing->inflight_ops -= f->nops;
  ing->c_done_ops.fetch_add(f->nops, std::memory_order_relaxed);
}

// Decode one fe_batch frame on the LOOP THREAD (no GIL anywhere in here):
// columnar op buffers + native-interned key/value bytes, then wake the
// Python engine through the ingest eventfd.  Malformed/overload frames
// answer with an fe error frame — the client tears and retries, exactly
// the undecodable-frame economics of the pickle path.
void ingest_frame(Server* s, Ingest* ing, uint64_t conn_id,
                  const uint8_t* p, size_t n) {
  if (p[3] != fewire::kFeVersion) {
    s->wire_rejected.fetch_add(1, std::memory_order_relaxed);
    enqueue_reply(s, conn_id, fe_error_bytes("fe wire version mismatch"));
    return;
  }
  uint16_t flags = fewire::load<uint16_t>(p + 4);
  uint16_t nops = fewire::load<uint16_t>(p + 6);
  size_t off = fewire::kHdrSize;
  uint64_t tc0 = 0, tc1 = 0;
  uint32_t deadline_ms = 0;
  bool has_tc = (flags & fewire::kFlagTrace) != 0;
  bool want_crc = (flags & fewire::kFlagCrc) != 0;
  if (has_tc) {
    if (n < off + fewire::kTcSize) {
      s->wire_rejected.fetch_add(1, std::memory_order_relaxed);
      enqueue_reply(s, conn_id, fe_error_bytes("malformed fe_batch frame"));
      return;
    }
    tc0 = fewire::load<uint64_t>(p + off);
    tc1 = fewire::load<uint64_t>(p + off + 8);
    off += fewire::kTcSize;
  }
  if (flags & fewire::kFlagDeadline) {
    if (n < off + 4) {
      s->wire_rejected.fetch_add(1, std::memory_order_relaxed);
      enqueue_reply(s, conn_id, fe_error_bytes("malformed fe_batch frame"));
      return;
    }
    deadline_ms = fewire::load<uint32_t>(p + off);
    off += 4;
  }
  if (want_crc) {
    // Frame integrity (the netfault corrupt defense): crc32 over every
    // byte except the 4-byte crc field itself; a mismatch is a
    // connection-scoped reject, NEVER a silently-altered op.
    if (n < off + 4) {
      s->wire_rejected.fetch_add(1, std::memory_order_relaxed);
      enqueue_reply(s, conn_id, fe_error_bytes("malformed fe_batch frame"));
      return;
    }
    uint32_t want = fewire::load<uint32_t>(p + off);
    uint32_t got = fewire::crc32(p, off);
    got = fewire::crc32(p + off + 4, n - off - 4, got);
    if (got != want) {
      s->wire_rejected.fetch_add(1, std::memory_order_relaxed);
      enqueue_reply(s, conn_id,
                    fe_error_bytes("fe_batch frame CRC mismatch"));
      return;
    }
    off += 4;
  }
  if (nops == 0) {
    // Degenerate empty batch: answer now so the connection's reply FIFO
    // stays in sync (mirrors the Python engine's empty-frame handling).
    std::vector<uint8_t> out(fewire::kHdrSize, 0);
    out[0] = 'F';
    out[1] = 'E';
    out[2] = 'R';
    out[3] = fewire::kFeVersion;
    enqueue_reply(s, conn_id, std::move(out));
    return;
  }
  {
    std::lock_guard<std::mutex> g(ing->mu);
    if (ing->inflight_ops + nops > ing->max_ops) {
      ing->c_full.fetch_add(1, std::memory_order_relaxed);
      enqueue_reply(s, conn_id,
                    fe_error_bytes("native ingest overloaded (ring full)"));
      return;
    }
  }
  auto* f = new FeFrame;
  f->ts_ns = fewire::mono_ns();  // opscope: the frame-parse origin stamp
  f->conn_id = conn_id;
  f->nops = nops;
  f->remaining = nops;
  f->has_tc = has_tc;
  f->want_crc = want_crc;
  f->deadline_ms = deadline_ms;
  f->tc[0] = tc0;
  f->tc[1] = tc1;
  f->kind.reserve(nops);
  f->cid.reserve(nops);
  f->cseq.reserve(nops);
  f->key_id.reserve(nops);
  f->val_id.reserve(nops);
  f->err.assign(nops, 0);
  f->answered.assign(nops, 0);
  f->rep_val.assign(nops, -1);
  bool ok = true;
  for (uint16_t i = 0; i < nops; i++) {
    if (n < off + fewire::kOpFixed) {
      ok = false;
      break;
    }
    uint8_t kind = p[off];
    uint64_t cid = fewire::load<uint64_t>(p + off + 1);
    int64_t cseq = fewire::load<int64_t>(p + off + 9);
    uint16_t klen = fewire::load<uint16_t>(p + off + 17);
    uint32_t vlen = fewire::load<uint32_t>(p + off + 19);
    off += fewire::kOpFixed;
    if (kind >= fewire::kNumKinds || n < off + klen + vlen) {
      ok = false;
      break;
    }
    int32_t kid = intern_core::store_put(
        &ing->keys, reinterpret_cast<const char*>(p + off), klen, nullptr);
    off += klen;
    int32_t vid = -1;
    if (vlen > 0) {
      vid = intern_core::store_put(
          &ing->vals, reinterpret_cast<const char*>(p + off), vlen, nullptr);
    }
    off += vlen;
    f->kind.push_back(int32_t(kind));
    f->cid.push_back(int64_t(cid));
    f->cseq.push_back(cseq);
    f->key_id.push_back(kid);
    f->val_id.push_back(vid);
  }
  if (!ok || off != n) {
    // Roll back the interns taken so far; the frame never existed.
    for (size_t i = 0; i < f->key_id.size(); i++) {
      intern_core::store_decref(&ing->keys, f->key_id[i]);
      if (f->val_id[i] >= 0)
        intern_core::store_decref(&ing->vals, f->val_id[i]);
    }
    delete f;
    s->wire_rejected.fetch_add(1, std::memory_order_relaxed);
    enqueue_reply(s, conn_id, fe_error_bytes("malformed fe_batch frame"));
    return;
  }
  {
    std::lock_guard<std::mutex> g(ing->mu);
    f->id = ing->next_frame++;
    ing->frames.emplace(f->id, f);
    ing->fresh.push_back(f->id);
    ing->inflight_ops += nops;
  }
  ing->c_frames.fetch_add(1, std::memory_order_relaxed);
  ing->c_ops.fetch_add(nops, std::memory_order_relaxed);
  ing->c_bytes.fetch_add(int64_t(n), std::memory_order_relaxed);
  ingest_wake_engine(ing);
}

// Hand the next buffered complete frame (if any) to the callback.  Called
// from handle_read and after a reply flush (the client may have sent its
// next pooled request while the previous one was being served).  Per-REQUEST
// fault injection and rpc counting live here: a request-drop closes the
// connection with the frame unprocessed — for a pooled client that is a
// torn connection + redial, the reference's per-connection economics.
// Returns false when the connection was closed.
bool try_dispatch(Server* s, uint64_t id, Conn& c) {
  if (c.handed_off || c.rbuf.size() < 4) return true;
  size_t len = (size_t(c.rbuf[0]) << 24) | (size_t(c.rbuf[1]) << 16) |
               (size_t(c.rbuf[2]) << 8) | size_t(c.rbuf[3]);
  if (len > kMaxFrame) {
    // Oversized frame claim (or a corrupted length prefix): reject the
    // CONNECTION, count it, keep serving everyone else.
    s->wire_rejected.fetch_add(1, std::memory_order_relaxed);
    close_conn(s, id);
    return false;
  }
  if (c.rbuf.size() < 4 + len) return true;
  s->rpc_count.fetch_add(1, std::memory_order_relaxed);
  bool unrel = s->unreliable.load(std::memory_order_relaxed);
  double r1 = next_unit(s->rng), r2 = next_unit(s->rng);
  if (unrel && r1 < kReqDrop) {  // discard unprocessed: op NOT executed
    close_conn(s, id);
    return false;
  }
  c.discard_reply = unrel && r2 < kRepDrop;
  c.handed_off = true;  // one request in flight per connection
  c.deadline_ms = now_ms() + s->io_deadline_ms.load(std::memory_order_relaxed);
  epoll_mod(s, id, c);
  const uint8_t* payload = c.rbuf.data() + 4;
  Ingest* ing_ = s->ingest.load(std::memory_order_acquire);
  if (ing_ != nullptr && fewire::is_batch(payload, len)) {
    // Native fe_batch frame: decode HERE, on the loop thread, into the
    // columnar ingest buffers — the Python callback never sees it.
    ingest_frame(s, ing_, id, payload, len);
  } else {
    s->cb(id, payload, int64_t(len));
  }
  // The callback/decoder consumes the payload synchronously; drop it.
  c.rbuf.erase(c.rbuf.begin(), c.rbuf.begin() + 4 + len);
  return true;
}

void handle_read(Server* s, uint64_t id) {
  auto it = s->conns.find(id);
  if (it == s->conns.end()) return;
  Conn& c = it->second;
  uint8_t buf[65536];
  bool eof = false;
  for (;;) {
    ssize_t n = read(c.fd, buf, sizeof buf);
    if (n > 0) {
      c.rbuf.insert(c.rbuf.end(), buf, buf + n);
      if (c.rbuf.size() > kMaxFrame + 4) {
        close_conn(s, id);
        return;
      }
      continue;
    }
    if (n < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) break;
    eof = true;  // a buffered complete frame is still served (the client
    break;       // may legally send-then-SHUT_WR and wait for the reply)
  }
  if (!try_dispatch(s, id, c)) return;
  if (eof && !c.handed_off && !c.want_write)
    close_conn(s, id);  // hung up with nothing in flight
}

void handle_write(Server* s, uint64_t id) {
  auto it = s->conns.find(id);
  if (it == s->conns.end()) return;
  Conn& c = it->second;
  while (c.woff < c.wbuf.size()) {
    if (c.pace_ms && now_ms() < c.next_write_ms)
      return;  // stalled reply: the loop tick resumes the trickle
    size_t want = c.wbuf.size() - c.woff;
    if (c.write_cap && want > c.write_cap) want = c.write_cap;
    ssize_t n = write(c.fd, c.wbuf.data() + c.woff, want);
    if (n > 0) {
      c.woff += size_t(n);
      if (c.pace_ms) {
        c.next_write_ms = now_ms() + c.pace_ms;
        if (c.woff < c.wbuf.size()) return;
      }
      continue;
    }
    if (n < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) return;
    close_conn(s, id);
    return;
  }
  if (c.pace_ms) {
    s->paced.fetch_sub(1, std::memory_order_relaxed);
    c.pace_ms = 0;  // cleared BEFORE close_conn so it never re-counts
  }
  if (c.close_after_write) {  // netfault truncate/dup: tear once flushed
    close_conn(s, id);
    return;
  }
  // Reply fully written → reset for the next pooled request on this
  // connection (a dial-per-call client just hangs up instead; the read
  // side then sees EOF and closes).
  c.wbuf.clear();
  c.woff = 0;
  c.want_write = false;
  c.write_cap = 0;
  c.pace_ms = 0;
  c.handed_off = false;
  c.discard_reply = false;
  c.deadline_ms = now_ms() + s->io_deadline_ms.load(std::memory_order_relaxed);
  epoll_mod(s, id, c);
  try_dispatch(s, id, c);  // next request may already be buffered
}

// Draw the next netfault reply fault for this server: armed FIFO first,
// then the seeded plan (two rng draws per reply, like durafs.FaultPlan,
// so placement is a pure function of the reply index).  Returns kind or
// -1, with frac in *frac_out.
int nf_draw(Server* s, double* frac_out) {
  std::lock_guard<std::mutex> g(s->nf_mu);
  s->nf_index++;
  if (!s->nf_armed.empty()) {
    auto [kind, frac] = s->nf_armed.front();
    s->nf_armed.pop_front();
    *frac_out = frac;
    return kind;
  }
  if (!s->nf_plan) return -1;
  double u = next_unit(s->nf_rng), frac = next_unit(s->nf_rng);
  double acc = 0.0;
  for (int k = 0; k < kNumNetFaults; k++) {
    acc += s->nf_rates[k];
    if (u < acc) {
      *frac_out = frac;
      return k;
    }
  }
  return -1;
}

void drain_replies(Server* s) {
  std::deque<Reply> batch;
  {
    std::lock_guard<std::mutex> g(s->mu);
    batch.swap(s->pending);
  }
  for (Reply& r : batch) {
    auto it = s->conns.find(r.conn_id);
    if (it == s->conns.end()) continue;  // client gone meanwhile
    Conn& c = it->second;
    if (r.data.empty()) {  // close-only marker: drop without replying
      close_conn(s, r.conn_id);
      continue;
    }
    if (c.discard_reply) {
      // Executed, but the client sees a dead connection — SHUT_WR
      // (paxos/paxos.go:535-538).
      shutdown(c.fd, SHUT_WR);
      close_conn(s, r.conn_id);
      continue;
    }
    uint32_t len = uint32_t(r.data.size());
    c.wbuf.resize(4 + r.data.size());
    c.wbuf[0] = uint8_t(len >> 24);
    c.wbuf[1] = uint8_t(len >> 16);
    c.wbuf[2] = uint8_t(len >> 8);
    c.wbuf[3] = uint8_t(len);
    memcpy(c.wbuf.data() + 4, r.data.data(), r.data.size());
    // netfault (ISSUE 12): byte-level reply faults — the hook that
    // makes NATIVE-INGEST connections injectable (their request path
    // never re-enters Python, so the Python seam cannot see them).
    double frac = 0.5;
    int nf = nf_draw(s, &frac);
    if (nf >= 0) {
      s->nf_injected.fetch_add(1, std::memory_order_relaxed);
      size_t total = c.wbuf.size();
      switch (nf) {
        case kNfCorrupt: {
          // 1-3 flips at offsets that are a PURE function of (reply
          // index, frac, length) — the Python corrupt_offsets rule —
          // anywhere in the framed bytes, length prefix included (the
          // client decode state machine owes safety everywhere).
          // NEVER seed from s->rng: it advances with the unreliable
          // coins per request, which would break seed replay.
          uint64_t rr = (s->nf_index << 20) ^ uint64_t(frac * 1e6) ^
                        uint64_t(total);
          if (rr == 0) rr = 1;  // xorshift state must be nonzero
          int nflips = 1 + int(next_unit(rr) * 3);
          for (int k = 0; k < nflips; k++)
            c.wbuf[size_t(next_unit(rr) * total)] ^= 0xFF;
          break;
        }
        case kNfTruncate: {
          size_t keep = total * std::min(std::max(frac, 0.01), 0.95);
          c.wbuf.resize(std::max<size_t>(1, keep));
          c.close_after_write = true;
          break;
        }
        case kNfSplit:
        case kNfCoalesce:
          c.write_cap = std::max<size_t>(1, std::min<size_t>(512,
                            total * std::min(std::max(frac, 0.02), 0.5)));
          break;
        case kNfStall:
          c.write_cap = std::max<size_t>(128, total / 8);
          c.pace_ms = 40 + int64_t(frac * 80);
          c.next_write_ms = 0;
          s->paced.fetch_add(1, std::memory_order_relaxed);
          break;
        case kNfDup:
          // Reply-direction "duplicate": the fe reply wire has no
          // request ids, so a literally-doubled reply would be
          // UNDETECTABLE by any client (the next request would read
          // the stale copy) — that would manufacture violations no
          // server code could prevent.  Model the delivered-once half
          // instead: reply flushed, then the conn torn, forcing the
          // client through redial + resend, where the REQUEST-side dup
          // filter (exercised by the Python injector's true dup_frame)
          // absorbs the replay.
          c.close_after_write = true;
          break;
        case kNfReset:
          close_conn(s, r.conn_id);
          continue;
      }
    }
    c.want_write = true;
    // Re-arm the I/O deadline for the reply-write phase: a client that
    // stops reading must not pin the fd + buffered reply forever.
    c.deadline_ms = now_ms() + s->io_deadline_ms.load(std::memory_order_relaxed);
    epoll_mod(s, r.conn_id, c);
    handle_write(s, r.conn_id);  // opportunistic immediate flush
    if (r.t_ns) {
      // opscope flush stage (ISSUE 15): reply-ring completion →
      // serialize + the loop's flush attempt, per fe frame.  The rare
      // partial write that finishes on a later EPOLLOUT is attributed
      // to the attempt that staged it — batch-granular telemetry, and
      // the loop never tracks per-reply state past this point.
      Ingest* ing = s->ingest.load(std::memory_order_acquire);
      if (ing != nullptr) {
        int64_t us = (fewire::mono_ns() - r.t_ns) / 1000;
        ing->fl_buckets[fewire::log2_bucket_us(us)].fetch_add(
            1, std::memory_order_relaxed);
        ing->fl_count.fetch_add(1, std::memory_order_relaxed);
        ing->fl_sum_us.fetch_add(us > 0 ? us : 0,
                                 std::memory_order_relaxed);
      }
    }
  }
}

void sweep_stale(Server* s) {
  // The 30s deadline bounds socket I/O phases only — request read
  // (pre-handoff) and reply write (want_write, deadline re-armed when the
  // reply is enqueued) — matching the Python transport's settimeout(30.0),
  // which likewise never bounds handler execution.  A handed-off
  // connection whose handler is still running (no reply yet) is exempt.
  int64_t now = now_ms();
  std::vector<uint64_t> stale;
  for (auto& [id, c] : s->conns)
    if ((!c.handed_off || c.want_write) && now >= c.deadline_ms)
      stale.push_back(id);
  for (uint64_t id : stale) close_conn(s, id);
}

void loop_body(Server* s) {
  epoll_event evs[64];
  int64_t next_sweep = now_ms() + 1000;
  while (!s->dead.load(std::memory_order_acquire)) {
    // Stalled (netfault-paced) replies are resumed by the loop tick,
    // not EPOLLOUT (see epoll_mod) — shorten the tick while any exist.
    int tmo = s->paced.load(std::memory_order_relaxed) > 0 ? 20 : 200;
    int n = epoll_wait(s->epfd, evs, 64, tmo);
    if (s->paced.load(std::memory_order_relaxed) > 0) {
      int64_t now = now_ms();
      std::vector<uint64_t> due;
      for (auto& [id, c] : s->conns)
        if (c.pace_ms && c.woff < c.wbuf.size() && now >= c.next_write_ms)
          due.push_back(id);
      for (uint64_t id : due) handle_write(s, id);
    }
    if (now_ms() >= next_sweep) {
      sweep_stale(s);
      next_sweep = now_ms() + 1000;
    }
    for (int i = 0; i < n; i++) {
      uint64_t id = evs[i].data.u64;
      if (id == 0) {  // listener
        handle_accept(s);
      } else if (id == 1) {  // eventfd: replies pending
        uint64_t junk;
        while (read(s->evfd, &junk, 8) == 8) {
        }
        drain_replies(s);
      } else {
        if (evs[i].events & (EPOLLHUP | EPOLLERR)) {
          close_conn(s, id);
          continue;
        }
        if (evs[i].events & EPOLLIN) handle_read(s, id);
        if (evs[i].events & EPOLLOUT) handle_write(s, id);
      }
    }
  }
  for (auto& [id, c] : s->conns) close(c.fd);
  s->conns.clear();
}

}  // namespace

extern "C" {

void* rpcsrv_start(const char* path, uint64_t seed, Callback cb) {
  sockaddr_un addr{};
  if (strlen(path) >= sizeof(addr.sun_path)) return nullptr;  // would
  // silently truncate and bind a different path than requested
  auto* s = new Server;
  s->path = path;
  s->rng = seed ? seed : 0x9e3779b97f4a7c15ull;
  s->cb = cb;
  unlink(path);
  s->lfd = socket(AF_UNIX, SOCK_STREAM | SOCK_NONBLOCK, 0);
  addr.sun_family = AF_UNIX;
  strncpy(addr.sun_path, path, sizeof(addr.sun_path) - 1);
  if (s->lfd < 0 || bind(s->lfd, (sockaddr*)&addr, sizeof addr) != 0 ||
      listen(s->lfd, 128) != 0) {
    if (s->lfd >= 0) close(s->lfd);
    delete s;
    return nullptr;
  }
  s->epfd = epoll_create1(0);
  s->evfd = eventfd(0, EFD_NONBLOCK);
  epoll_event ev{};
  ev.events = EPOLLIN;
  ev.data.u64 = 0;  // listener sentinel
  epoll_ctl(s->epfd, EPOLL_CTL_ADD, s->lfd, &ev);
  ev.data.u64 = 1;  // eventfd sentinel
  epoll_ctl(s->epfd, EPOLL_CTL_ADD, s->evfd, &ev);
  s->next_id = 2;
  s->loop = std::thread(loop_body, s);
  return s;
}

void rpcsrv_reply(void* srv, uint64_t conn_id, const uint8_t* data,
                  int64_t len) {
  auto* s = static_cast<Server*>(srv);
  if (s->dead.load(std::memory_order_acquire)) return;
  {
    std::lock_guard<std::mutex> g(s->mu);
    s->pending.push_back(
        Reply{conn_id, std::vector<uint8_t>(data, data + len)});
  }
  uint64_t one = 1;
  ssize_t ignored = write(s->evfd, &one, 8);
  (void)ignored;
}

void rpcsrv_set_unreliable(void* srv, int flag) {
  static_cast<Server*>(srv)->unreliable.store(flag != 0,
                                              std::memory_order_relaxed);
}

// ---------------------------------------------------------- netfault
// Reply-path byte-fault injection (ISSUE 12).  kind indexes
// rpc/netfault.py NET_FAULT_KINDS; armed faults fire FIFO against the
// server's reply sequence, a seeded plan draws per reply (two xorshift
// draws each, durafs.FaultPlan style).

void rpcsrv_netfault_arm(void* srv, int kind, double frac) {
  auto* s = static_cast<Server*>(srv);
  if (kind < 0 || kind >= kNumNetFaults) return;
  std::lock_guard<std::mutex> g(s->nf_mu);
  s->nf_armed.emplace_back(kind, frac);
}

void rpcsrv_netfault_plan(void* srv, uint64_t seed, const double* rates) {
  auto* s = static_cast<Server*>(srv);
  std::lock_guard<std::mutex> g(s->nf_mu);
  s->nf_rng = seed ? seed : 1;
  for (int k = 0; k < kNumNetFaults; k++) s->nf_rates[k] = rates[k];
  s->nf_plan = true;
}

void rpcsrv_netfault_clear(void* srv) {
  auto* s = static_cast<Server*>(srv);
  std::lock_guard<std::mutex> g(s->nf_mu);
  s->nf_armed.clear();
  s->nf_plan = false;
}

int64_t rpcsrv_netfault_injected(void* srv) {
  return static_cast<Server*>(srv)->nf_injected.load(
      std::memory_order_relaxed);
}

int64_t rpcsrv_wire_rejected(void* srv) {
  return static_cast<Server*>(srv)->wire_rejected.load(
      std::memory_order_relaxed);
}

void rpcsrv_set_io_deadline_ms(void* srv, int64_t ms) {
  static_cast<Server*>(srv)->io_deadline_ms.store(
      ms > 0 ? ms : kConnTimeoutMs, std::memory_order_relaxed);
}

int64_t rpcsrv_rpc_count(void* srv) {
  return static_cast<Server*>(srv)->rpc_count.load(
      std::memory_order_relaxed);
}

void rpcsrv_deafen(void* srv) {
  // Remove the socket path out from under the live server: the inode keeps
  // listening but nobody can dial it (paxos/test_test.go:194-195).
  unlink(static_cast<Server*>(srv)->path.c_str());
}

void rpcsrv_kill(void* srv) {
  // Stops the loop and closes sockets; does NOT free — the embedder calls
  // rpcsrv_free after it has guaranteed no thread can still call
  // rpcsrv_reply (the Python wrapper serializes reply/kill/free on a lock).
  auto* s = static_cast<Server*>(srv);
  if (s->dead.exchange(true, std::memory_order_acq_rel)) return;
  uint64_t one = 1;
  ssize_t ignored = write(s->evfd, &one, 8);
  (void)ignored;
  if (s->loop.joinable()) s->loop.join();
  close(s->lfd);
  close(s->epfd);
  close(s->evfd);
  unlink(s->path.c_str());
}

void rpcsrv_free(void* srv) {
  auto* s = static_cast<Server*>(srv);
  Ingest* ing = s->ingest.load(std::memory_order_acquire);
  if (ing != nullptr) {
    for (auto& [id, f] : ing->frames) delete f;
    if (ing->efd >= 0) close(ing->efd);
    delete ing;
    s->ingest.store(nullptr, std::memory_order_release);
  }
  delete s;
}

// ------------------------------------------------------- native ingest

// Enable zero-GIL ingest (right after the server binds, before traffic;
// the pointer is published atomically, a racing frame just takes the
// Python callback once): fe_batch frames decode on the loop thread into
// columnar buffers.  Returns the engine-wakeup eventfd
// (Python selects on it; the loop writes it per ingested frame), or -1.
int rpcsrv_ingest_enable(void* srv, int64_t max_ops) {
  auto* s = static_cast<Server*>(srv);
  Ingest* have = s->ingest.load(std::memory_order_acquire);
  if (have != nullptr) return have->efd;
  auto* ing = new Ingest;
  if (max_ops > 0) ing->max_ops = max_ops;
  ing->efd = eventfd(0, EFD_NONBLOCK);
  if (ing->efd < 0) {
    delete ing;
    return -1;
  }
  s->ingest.store(ing, std::memory_order_release);
  return ing->efd;
}

// Pop one ready frame: hdr8 = {frame_id, conn_id, nops, has_tc, tc0, tc1,
// deadline_ms (0 = none — the propagated clerk op budget), ts_ns (the
// loop thread's frame-parse monotonic stamp — opscope's ingest-ring ts
// column, per-frame value)},
// columns memcpy'd into the caller's buffers (cap ops each).  Returns nops,
// -1 when no frame is ready, -2 when cap is too small (frame stays
// queued).  The frame's column storage is released here — the caller's
// copies are the only ones left; err/answered bookkeeping stays for the
// reply path.
int64_t rpcsrv_ingest_poll1(void* srv, uint64_t* hdr, int32_t* kinds,
                            int64_t* cids, int64_t* cseqs, int32_t* keyids,
                            int32_t* valids, int64_t cap) {
  auto* s = static_cast<Server*>(srv);
  Ingest* ing = s->ingest.load(std::memory_order_acquire);
  if (ing == nullptr) return -1;
  std::lock_guard<std::mutex> g(ing->mu);
  while (!ing->fresh.empty()) {
    uint64_t fid = ing->fresh.front();
    auto it = ing->frames.find(fid);
    if (it == ing->frames.end()) {
      ing->fresh.pop_front();
      continue;
    }
    FeFrame* f = it->second;
    if (int64_t(f->nops) > cap) return -2;
    ing->fresh.pop_front();
    hdr[0] = f->id;
    hdr[1] = f->conn_id;
    hdr[2] = f->nops;
    hdr[3] = f->has_tc ? 1 : 0;
    hdr[4] = f->tc[0];
    hdr[5] = f->tc[1];
    hdr[6] = f->deadline_ms;
    hdr[7] = uint64_t(f->ts_ns);
    memcpy(kinds, f->kind.data(), f->nops * sizeof(int32_t));
    memcpy(cids, f->cid.data(), f->nops * sizeof(int64_t));
    memcpy(cseqs, f->cseq.data(), f->nops * sizeof(int64_t));
    memcpy(keyids, f->key_id.data(), f->nops * sizeof(int32_t));
    memcpy(valids, f->val_id.data(), f->nops * sizeof(int32_t));
    std::vector<int32_t>().swap(f->kind);
    std::vector<int64_t>().swap(f->cid);
    std::vector<int64_t>().swap(f->cseq);
    std::vector<int32_t>().swap(f->key_id);
    std::vector<int32_t>().swap(f->val_id);
    return int64_t(f->nops);
  }
  return -1;
}

// Intern one reply value (get results) into the vals store, ref 1 —
// ownership passes to the next rpcsrv_ingest_push that places it (or is
// dropped there if the slot is gone).
int32_t rpcsrv_ingest_val_intern(void* srv, const char* data, int64_t len) {
  auto* s = static_cast<Server*>(srv);
  Ingest* ing = s->ingest.load(std::memory_order_acquire);
  if (ing == nullptr) return -1;
  return intern_core::store_put(&ing->vals, data, len, nullptr);
}

// Batched reply-value intern: `data` is n values concatenated,
// offs/lens index it, ids land in `out` — ONE FFI transition per notify
// sweep instead of one per get reply (the sweep runs under the kvpaxos
// server mutex; per-op lock round-trips there are the round-13 lesson).
void rpcsrv_ingest_val_intern_many(void* srv, const char* data,
                                   const int64_t* offs,
                                   const int64_t* lens, int32_t* out,
                                   int64_t n) {
  auto* s = static_cast<Server*>(srv);
  Ingest* ing = s->ingest.load(std::memory_order_acquire);
  if (ing == nullptr) {
    for (int64_t i = 0; i < n; i++) out[i] = -1;
    return;
  }
  for (int64_t i = 0; i < n; i++)
    out[i] = intern_core::store_put(&ing->vals, data + offs[i], lens[i],
                                    nullptr);
}

// The reply ring's write side: (tag, err, rep_val) triples from the
// driver's notify sweep.  tag = (frame_id << 16) | slot.  Unknown frames
// and already-answered slots are ignored (a second replica applying the
// same decided op pushes the same tag); a frame whose last slot lands
// here is serialized and flushed by the loop.
void rpcsrv_ingest_push(void* srv, const int64_t* tags, const uint8_t* errs,
                        const int32_t* repvals, int64_t n) {
  auto* s = static_cast<Server*>(srv);
  Ingest* ing = s->ingest.load(std::memory_order_acquire);
  if (ing == nullptr) return;
  std::lock_guard<std::mutex> g(ing->mu);
  for (int64_t i = 0; i < n; i++) {
    uint64_t fid = uint64_t(tags[i]) >> 16;
    uint32_t slot = uint32_t(tags[i] & 0xFFFF);
    auto it = ing->frames.find(fid);
    FeFrame* f = it == ing->frames.end() ? nullptr : it->second;
    if (f == nullptr || slot >= f->nops || f->answered[slot] ||
        f->remaining == 0) {
      if (repvals[i] >= 0)
        intern_core::store_decref(&ing->vals, repvals[i]);
      continue;
    }
    f->answered[slot] = 1;
    f->err[slot] = errs[i];
    f->rep_val[slot] = repvals[i];
    if (--f->remaining == 0) fe_complete_locked(s, ing, f);
  }
}

// Unanswered slot indices for a live frame (the engine's retry pass);
// returns the count, or -1 for an unknown frame.  `out` must hold nops.
int64_t rpcsrv_ingest_pending(void* srv, uint64_t frame_id, int32_t* out) {
  auto* s = static_cast<Server*>(srv);
  Ingest* ing = s->ingest.load(std::memory_order_acquire);
  if (ing == nullptr) return -1;
  std::lock_guard<std::mutex> g(ing->mu);
  auto it = ing->frames.find(frame_id);
  if (it == ing->frames.end()) return -1;
  FeFrame* f = it->second;
  int64_t n = 0;
  for (uint32_t i = 0; i < f->nops; i++)
    if (!f->answered[i]) out[n++] = int32_t(i);
  return n;
}

// Fail a live frame (engine timeout): fe error reply to the client, frame
// retired to the reap queue.  Late pushes against it are dropped.
void rpcsrv_ingest_fail(void* srv, uint64_t frame_id, const char* msg) {
  auto* s = static_cast<Server*>(srv);
  Ingest* ing = s->ingest.load(std::memory_order_acquire);
  if (ing == nullptr) return;
  std::lock_guard<std::mutex> g(ing->mu);
  auto it = ing->frames.find(frame_id);
  if (it == ing->frames.end()) return;
  FeFrame* f = it->second;
  if (f->remaining == 0) return;  // already completed
  for (uint32_t i = 0; i < f->nops; i++) {
    f->answered[i] = 1;
    if (f->rep_val[i] >= 0) {
      intern_core::store_decref(&ing->vals, f->rep_val[i]);
      f->rep_val[i] = -1;
    }
  }
  f->remaining = 0;
  enqueue_reply(s, f->conn_id, fe_error_bytes(msg));
  ing->done.push_back(f->id);
  ing->inflight_ops -= f->nops;
  ing->c_done_ops.fetch_add(f->nops, std::memory_order_relaxed);
}

// Pop completed/failed frame ids (the engine's bookkeeping reap); the
// frame structs are freed here — request-side key/value intern refs are
// the ENGINE's to drop (it holds the column copies), via
// rpcsrv_ingest_decref once materialization has provably drained.
int64_t rpcsrv_ingest_reap(void* srv, uint64_t* out, int64_t cap) {
  auto* s = static_cast<Server*>(srv);
  Ingest* ing = s->ingest.load(std::memory_order_acquire);
  if (ing == nullptr) return 0;
  std::lock_guard<std::mutex> g(ing->mu);
  int64_t n = 0;
  while (n < cap && !ing->done.empty()) {
    uint64_t fid = ing->done.front();
    ing->done.pop_front();
    auto it = ing->frames.find(fid);
    if (it != ing->frames.end()) {
      delete it->second;
      ing->frames.erase(it);
    }
    out[n++] = fid;
  }
  return n;
}

// Copy a live interned payload out of the key (which=0) / value (which=1)
// store: returns length (> cap: nothing copied, retry bigger), -1 freed.
int64_t rpcsrv_ingest_get(void* srv, int which, int32_t id, char* out,
                          int64_t cap) {
  auto* s = static_cast<Server*>(srv);
  Ingest* ing = s->ingest.load(std::memory_order_acquire);
  if (ing == nullptr) return -1;
  return intern_core::store_get_copy(which ? &ing->vals : &ing->keys, id,
                                     out, cap);
}

// Columnar decref over the key/value store; ids < 0 are skipped.  Freed
// ids are written to `freed` (the Python mirror invalidates its cached
// strings for exactly those), count returned.
int64_t rpcsrv_ingest_decref(void* srv, int which, const int32_t* ids,
                             int64_t n, int32_t* freed) {
  auto* s = static_cast<Server*>(srv);
  Ingest* ing = s->ingest.load(std::memory_order_acquire);
  if (ing == nullptr) return 0;
  intern_core::Store* st = which ? &ing->vals : &ing->keys;
  int64_t nf = 0;
  for (int64_t i = 0; i < n; i++)
    if (ids[i] >= 0 && intern_core::store_decref(st, ids[i]))
      freed[nf++] = ids[i];
  return nf;
}

// opscope flush-stage histogram (ISSUE 15), cumulative: out[0..63] =
// log2 µs buckets, out[64] = count, out[65] = µs sum.  The Python
// engine mirrors DELTAS into the registry once per pass — one FFI call,
// batch-columnar like every opscope fold.
void rpcsrv_opscope_flush(void* srv, int64_t* out) {
  auto* s = static_cast<Server*>(srv);
  Ingest* ing = s->ingest.load(std::memory_order_acquire);
  if (ing == nullptr) {
    memset(out, 0, 66 * sizeof(int64_t));
    return;
  }
  for (int k = 0; k < 64; k++)
    out[k] = ing->fl_buckets[k].load(std::memory_order_relaxed);
  out[64] = ing->fl_count.load(std::memory_order_relaxed);
  out[65] = ing->fl_sum_us.load(std::memory_order_relaxed);
}

// {frames, ops, bytes, ring_full, inflight_ops, live_frames, keys_live,
//  vals_live, done_ops} — the native_ingest counters the registry mirrors.
void rpcsrv_ingest_stats(void* srv, int64_t* out) {
  auto* s = static_cast<Server*>(srv);
  Ingest* ing = s->ingest.load(std::memory_order_acquire);
  if (ing == nullptr) {
    memset(out, 0, 9 * sizeof(int64_t));
    return;
  }
  out[0] = ing->c_frames.load(std::memory_order_relaxed);
  out[1] = ing->c_ops.load(std::memory_order_relaxed);
  out[2] = ing->c_bytes.load(std::memory_order_relaxed);
  out[3] = ing->c_full.load(std::memory_order_relaxed);
  {
    std::lock_guard<std::mutex> g(ing->mu);
    out[4] = ing->inflight_ops;
    out[5] = int64_t(ing->frames.size());
  }
  out[6] = intern_core::store_nlive(&ing->keys);
  out[7] = intern_core::store_nlive(&ing->vals);
  out[8] = ing->c_done_ops.load(std::memory_order_relaxed);
}

}  // extern "C"
