// Native L0 transport server — epoll event loop for the Unix-socket RPC
// endpoints (the runtime under tpu6824/rpc/native_server.py).
//
// The reference's per-server accept loop is its runtime kernel: it owns the
// listening socket, injects faults (drop 10% of connections unprocessed,
// serve-but-discard 20% of replies via SHUT_WR), and counts RPCs
// (paxos/paxos.go:524-552).  This is that loop as a native event loop:
// one epoll thread per server handles accept/read/write for every
// connection; request payloads are handed to the embedding runtime through
// a callback; replies come back on ANY thread via rpcsrv_reply (eventfd
// wakeup), so slow handlers never stall the loop.
//
// Framing matches tpu6824/rpc/transport.py: 4-byte big-endian length prefix,
// opaque payload (the codec lives above).  Semantics mirrored from the
// Python Server: connections are PERSISTENT (the pooled client default —
// many requests per connection; a dial-per-call client simply sends one),
// rpc_count increments per served request, and the fault coins are drawn
// per REQUEST with every injected fault tearing the connection down: the
// request-drop path discards the frame unprocessed, the reply-discard path
// executes the handler then SHUT_WR so the client sees a dead connection
// after the op ran — the executed-but-unacked case the at-most-once
// machinery upstairs is tested against.
//
// C ABI only; loaded via ctypes (no pybind11 in this image).

#include <atomic>
#include <cerrno>
#include <cstdint>
#include <cstring>
#include <deque>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

#include <chrono>

#include <fcntl.h>
#include <sys/epoll.h>
#include <sys/eventfd.h>
#include <sys/socket.h>
#include <sys/un.h>
#include <unistd.h>

namespace {

constexpr size_t kMaxFrame = 64ull << 20;
constexpr double kReqDrop = 0.10;  // paxos/paxos.go:528-531
constexpr double kRepDrop = 0.20;  // paxos/paxos.go:535-538
constexpr int64_t kConnTimeoutMs = 30'000;  // transport.py settimeout(30.0)

int64_t now_ms() {
  return std::chrono::duration_cast<std::chrono::milliseconds>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

using Callback = void (*)(uint64_t conn_id, const uint8_t* data,
                          int64_t len);

struct Conn {
  int fd = -1;
  bool discard_reply = false;  // fault drawn for the CURRENT request
  bool handed_off = false;     // one request in flight per connection
  bool want_write = false;
  int64_t deadline_ms = 0;   // absolute steady-clock ms; 30s per I/O phase
  std::vector<uint8_t> rbuf;
  std::vector<uint8_t> wbuf;
  size_t woff = 0;
};

struct Reply {
  uint64_t conn_id;
  std::vector<uint8_t> data;
};

struct Server {
  int lfd = -1, epfd = -1, evfd = -1;
  std::string path;
  std::atomic<bool> dead{false};
  std::atomic<bool> unreliable{false};
  std::atomic<int64_t> rpc_count{0};
  uint64_t rng;
  Callback cb;
  std::thread loop;
  std::mutex mu;  // guards pending
  std::deque<Reply> pending;
  std::unordered_map<uint64_t, Conn> conns;
  uint64_t next_id = 1;
};

double next_unit(uint64_t& s) {  // xorshift64*, uniform in [0,1)
  s ^= s >> 12;
  s ^= s << 25;
  s ^= s >> 27;
  return double((s * 2685821657736338717ull) >> 11) / double(1ull << 53);
}

void set_nonblock(int fd) {
  int fl = fcntl(fd, F_GETFL, 0);
  fcntl(fd, F_SETFL, fl | O_NONBLOCK);
}

void epoll_mod(Server* s, uint64_t id, Conn& c) {
  epoll_event ev{};
  ev.events = (c.handed_off ? 0u : unsigned(EPOLLIN)) |
              (c.want_write ? unsigned(EPOLLOUT) : 0u);
  ev.data.u64 = id;
  epoll_ctl(s->epfd, EPOLL_CTL_MOD, c.fd, &ev);
}

void close_conn(Server* s, uint64_t id) {
  auto it = s->conns.find(id);
  if (it == s->conns.end()) return;
  epoll_ctl(s->epfd, EPOLL_CTL_DEL, it->second.fd, nullptr);
  close(it->second.fd);
  s->conns.erase(it);
}

void handle_accept(Server* s) {
  for (;;) {
    int fd = accept4(s->lfd, nullptr, nullptr, SOCK_NONBLOCK);
    if (fd < 0) return;
    uint64_t id = s->next_id++;
    Conn& c = s->conns[id];
    c.fd = fd;
    c.deadline_ms = now_ms() + kConnTimeoutMs;
    epoll_event ev{};
    ev.events = EPOLLIN;
    ev.data.u64 = id;
    epoll_ctl(s->epfd, EPOLL_CTL_ADD, fd, &ev);
  }
}

// Hand the next buffered complete frame (if any) to the callback.  Called
// from handle_read and after a reply flush (the client may have sent its
// next pooled request while the previous one was being served).  Per-REQUEST
// fault injection and rpc counting live here: a request-drop closes the
// connection with the frame unprocessed — for a pooled client that is a
// torn connection + redial, the reference's per-connection economics.
// Returns false when the connection was closed.
bool try_dispatch(Server* s, uint64_t id, Conn& c) {
  if (c.handed_off || c.rbuf.size() < 4) return true;
  size_t len = (size_t(c.rbuf[0]) << 24) | (size_t(c.rbuf[1]) << 16) |
               (size_t(c.rbuf[2]) << 8) | size_t(c.rbuf[3]);
  if (len > kMaxFrame) {
    close_conn(s, id);
    return false;
  }
  if (c.rbuf.size() < 4 + len) return true;
  s->rpc_count.fetch_add(1, std::memory_order_relaxed);
  bool unrel = s->unreliable.load(std::memory_order_relaxed);
  double r1 = next_unit(s->rng), r2 = next_unit(s->rng);
  if (unrel && r1 < kReqDrop) {  // discard unprocessed: op NOT executed
    close_conn(s, id);
    return false;
  }
  c.discard_reply = unrel && r2 < kRepDrop;
  c.handed_off = true;  // one request in flight per connection
  c.deadline_ms = now_ms() + kConnTimeoutMs;
  epoll_mod(s, id, c);
  s->cb(id, c.rbuf.data() + 4, int64_t(len));
  // The callback copies the payload synchronously; drop the consumed frame.
  c.rbuf.erase(c.rbuf.begin(), c.rbuf.begin() + 4 + len);
  return true;
}

void handle_read(Server* s, uint64_t id) {
  auto it = s->conns.find(id);
  if (it == s->conns.end()) return;
  Conn& c = it->second;
  uint8_t buf[65536];
  bool eof = false;
  for (;;) {
    ssize_t n = read(c.fd, buf, sizeof buf);
    if (n > 0) {
      c.rbuf.insert(c.rbuf.end(), buf, buf + n);
      if (c.rbuf.size() > kMaxFrame + 4) {
        close_conn(s, id);
        return;
      }
      continue;
    }
    if (n < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) break;
    eof = true;  // a buffered complete frame is still served (the client
    break;       // may legally send-then-SHUT_WR and wait for the reply)
  }
  if (!try_dispatch(s, id, c)) return;
  if (eof && !c.handed_off && !c.want_write)
    close_conn(s, id);  // hung up with nothing in flight
}

void handle_write(Server* s, uint64_t id) {
  auto it = s->conns.find(id);
  if (it == s->conns.end()) return;
  Conn& c = it->second;
  while (c.woff < c.wbuf.size()) {
    ssize_t n = write(c.fd, c.wbuf.data() + c.woff, c.wbuf.size() - c.woff);
    if (n > 0) {
      c.woff += size_t(n);
      continue;
    }
    if (n < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) return;
    close_conn(s, id);
    return;
  }
  // Reply fully written → reset for the next pooled request on this
  // connection (a dial-per-call client just hangs up instead; the read
  // side then sees EOF and closes).
  c.wbuf.clear();
  c.woff = 0;
  c.want_write = false;
  c.handed_off = false;
  c.discard_reply = false;
  c.deadline_ms = now_ms() + kConnTimeoutMs;
  epoll_mod(s, id, c);
  try_dispatch(s, id, c);  // next request may already be buffered
}

void drain_replies(Server* s) {
  std::deque<Reply> batch;
  {
    std::lock_guard<std::mutex> g(s->mu);
    batch.swap(s->pending);
  }
  for (Reply& r : batch) {
    auto it = s->conns.find(r.conn_id);
    if (it == s->conns.end()) continue;  // client gone meanwhile
    Conn& c = it->second;
    if (r.data.empty()) {  // close-only marker: drop without replying
      close_conn(s, r.conn_id);
      continue;
    }
    if (c.discard_reply) {
      // Executed, but the client sees a dead connection — SHUT_WR
      // (paxos/paxos.go:535-538).
      shutdown(c.fd, SHUT_WR);
      close_conn(s, r.conn_id);
      continue;
    }
    uint32_t len = uint32_t(r.data.size());
    c.wbuf.resize(4 + r.data.size());
    c.wbuf[0] = uint8_t(len >> 24);
    c.wbuf[1] = uint8_t(len >> 16);
    c.wbuf[2] = uint8_t(len >> 8);
    c.wbuf[3] = uint8_t(len);
    memcpy(c.wbuf.data() + 4, r.data.data(), r.data.size());
    c.want_write = true;
    // Re-arm the I/O deadline for the reply-write phase: a client that
    // stops reading must not pin the fd + buffered reply forever.
    c.deadline_ms = now_ms() + kConnTimeoutMs;
    epoll_mod(s, r.conn_id, c);
    handle_write(s, r.conn_id);  // opportunistic immediate flush
  }
}

void sweep_stale(Server* s) {
  // The 30s deadline bounds socket I/O phases only — request read
  // (pre-handoff) and reply write (want_write, deadline re-armed when the
  // reply is enqueued) — matching the Python transport's settimeout(30.0),
  // which likewise never bounds handler execution.  A handed-off
  // connection whose handler is still running (no reply yet) is exempt.
  int64_t now = now_ms();
  std::vector<uint64_t> stale;
  for (auto& [id, c] : s->conns)
    if ((!c.handed_off || c.want_write) && now >= c.deadline_ms)
      stale.push_back(id);
  for (uint64_t id : stale) close_conn(s, id);
}

void loop_body(Server* s) {
  epoll_event evs[64];
  int64_t next_sweep = now_ms() + 1000;
  while (!s->dead.load(std::memory_order_acquire)) {
    int n = epoll_wait(s->epfd, evs, 64, 200);
    if (now_ms() >= next_sweep) {
      sweep_stale(s);
      next_sweep = now_ms() + 1000;
    }
    for (int i = 0; i < n; i++) {
      uint64_t id = evs[i].data.u64;
      if (id == 0) {  // listener
        handle_accept(s);
      } else if (id == 1) {  // eventfd: replies pending
        uint64_t junk;
        while (read(s->evfd, &junk, 8) == 8) {
        }
        drain_replies(s);
      } else {
        if (evs[i].events & (EPOLLHUP | EPOLLERR)) {
          close_conn(s, id);
          continue;
        }
        if (evs[i].events & EPOLLIN) handle_read(s, id);
        if (evs[i].events & EPOLLOUT) handle_write(s, id);
      }
    }
  }
  for (auto& [id, c] : s->conns) close(c.fd);
  s->conns.clear();
}

}  // namespace

extern "C" {

void* rpcsrv_start(const char* path, uint64_t seed, Callback cb) {
  sockaddr_un addr{};
  if (strlen(path) >= sizeof(addr.sun_path)) return nullptr;  // would
  // silently truncate and bind a different path than requested
  auto* s = new Server;
  s->path = path;
  s->rng = seed ? seed : 0x9e3779b97f4a7c15ull;
  s->cb = cb;
  unlink(path);
  s->lfd = socket(AF_UNIX, SOCK_STREAM | SOCK_NONBLOCK, 0);
  addr.sun_family = AF_UNIX;
  strncpy(addr.sun_path, path, sizeof(addr.sun_path) - 1);
  if (s->lfd < 0 || bind(s->lfd, (sockaddr*)&addr, sizeof addr) != 0 ||
      listen(s->lfd, 128) != 0) {
    if (s->lfd >= 0) close(s->lfd);
    delete s;
    return nullptr;
  }
  s->epfd = epoll_create1(0);
  s->evfd = eventfd(0, EFD_NONBLOCK);
  epoll_event ev{};
  ev.events = EPOLLIN;
  ev.data.u64 = 0;  // listener sentinel
  epoll_ctl(s->epfd, EPOLL_CTL_ADD, s->lfd, &ev);
  ev.data.u64 = 1;  // eventfd sentinel
  epoll_ctl(s->epfd, EPOLL_CTL_ADD, s->evfd, &ev);
  s->next_id = 2;
  s->loop = std::thread(loop_body, s);
  return s;
}

void rpcsrv_reply(void* srv, uint64_t conn_id, const uint8_t* data,
                  int64_t len) {
  auto* s = static_cast<Server*>(srv);
  if (s->dead.load(std::memory_order_acquire)) return;
  {
    std::lock_guard<std::mutex> g(s->mu);
    s->pending.push_back(
        Reply{conn_id, std::vector<uint8_t>(data, data + len)});
  }
  uint64_t one = 1;
  ssize_t ignored = write(s->evfd, &one, 8);
  (void)ignored;
}

void rpcsrv_set_unreliable(void* srv, int flag) {
  static_cast<Server*>(srv)->unreliable.store(flag != 0,
                                              std::memory_order_relaxed);
}

int64_t rpcsrv_rpc_count(void* srv) {
  return static_cast<Server*>(srv)->rpc_count.load(
      std::memory_order_relaxed);
}

void rpcsrv_deafen(void* srv) {
  // Remove the socket path out from under the live server: the inode keeps
  // listening but nobody can dial it (paxos/test_test.go:194-195).
  unlink(static_cast<Server*>(srv)->path.c_str());
}

void rpcsrv_kill(void* srv) {
  // Stops the loop and closes sockets; does NOT free — the embedder calls
  // rpcsrv_free after it has guaranteed no thread can still call
  // rpcsrv_reply (the Python wrapper serializes reply/kill/free on a lock).
  auto* s = static_cast<Server*>(srv);
  if (s->dead.exchange(true, std::memory_order_acq_rel)) return;
  uint64_t one = 1;
  ssize_t ignored = write(s->evfd, &one, 8);
  (void)ignored;
  if (s->loop.joinable()) s->loop.join();
  close(s->lfd);
  close(s->epfd);
  close(s->evfd);
  unlink(s->path.c_str());
}

void rpcsrv_free(void* srv) { delete static_cast<Server*>(srv); }

}  // extern "C"
