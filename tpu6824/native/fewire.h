// fe wire — C++ mirror of the versioned little-endian frame layout in
// tpu6824/rpc/wire.py (ISSUE 11).  The two files ARE the schema: any
// layout change bumps kFeVersion in BOTH, and an unknown version must be
// refused (error frame), never mis-parsed.
//
//   request  'F' 'E' 'B' ver |u16 flags|u16 nops| [u64 tid,u64 sid]
//            then nops records: u8 kind |u64 cid|i64 cseq|u16 klen|
//            u32 vlen| key bytes | value bytes
//   reply    'F' 'E' 'R' ver |u16 flags|u16 nops|
//            then nops records: u8 err |u32 vlen| value bytes
//   error    'F' 'E' 'E' ver |u32 mlen| utf-8 message
//
// Parsing uses memcpy loads (frames arrive unaligned in the connection
// read buffer) and assumes a little-endian host — the same assumption the
// Python struct '<' format encodes.

#pragma once

#include <cstdint>
#include <cstring>

namespace fewire {

constexpr uint8_t kFeVersion = 1;

// kind codes (closed enum, order is schema): get / put / append.
constexpr int32_t kKindGet = 0;
constexpr int32_t kKindPut = 1;
constexpr int32_t kKindAppend = 2;
constexpr int32_t kNumKinds = 3;

// err codes: OK / ErrNoKey / ErrWrongGroup; 255 = pickled escape hatch
// (only the Python encoder emits it).
constexpr uint8_t kErrOther = 255;

constexpr size_t kHdrSize = 8;       // magic4 + flags u16 + nops u16
constexpr size_t kTcSize = 16;       // trace_id u64 + span_id u64
constexpr size_t kOpFixed = 23;      // kind u8 + cid u64 + cseq i64 +
                                     // klen u16 + vlen u32
constexpr uint16_t kFlagTrace = 1;

inline bool is_batch(const uint8_t* p, size_t n) {
  return n >= kHdrSize && p[0] == 'F' && p[1] == 'E' && p[2] == 'B';
}

template <typename T>
inline T load(const uint8_t* p) {
  T v;
  memcpy(&v, p, sizeof(T));
  return v;
}

template <typename T>
inline void store(uint8_t* p, T v) {
  memcpy(p, &v, sizeof(T));
}

}  // namespace fewire
