// fe wire — C++ mirror of the versioned little-endian frame layout in
// tpu6824/rpc/wire.py (ISSUE 11).  The two files ARE the schema: any
// layout change bumps kFeVersion in BOTH, and an unknown version must be
// refused (error frame), never mis-parsed.
//
//   request  'F' 'E' 'B' ver |u16 flags|u16 nops| [u64 tid,u64 sid]
//            then nops records: u8 kind |u64 cid|i64 cseq|u16 klen|
//            u32 vlen| key bytes | value bytes
//   reply    'F' 'E' 'R' ver |u16 flags|u16 nops|
//            then nops records: u8 err |u32 vlen| value bytes
//   error    'F' 'E' 'E' ver |u32 mlen| utf-8 message
//
// Parsing uses memcpy loads (frames arrive unaligned in the connection
// read buffer) and assumes a little-endian host — the same assumption the
// Python struct '<' format encodes.

#pragma once

#include <chrono>
#include <cstdint>
#include <cstring>

namespace fewire {

// opscope (ISSUE 15) plumbing: the frame-parse timestamp stamped on the
// loop thread and the log2-µs bucketing rule shared with the Python
// metrics registry (bucket k = values with bit_length k).  steady_clock
// is CLOCK_MONOTONIC on Linux — the SAME clock CPython's
// time.monotonic_ns() reads, so C++ stamps subtract directly against
// Python-side stage stamps (the opscope monotonic-only invariant).
inline int64_t mono_ns() {
  return std::chrono::duration_cast<std::chrono::nanoseconds>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

inline int log2_bucket_us(int64_t us) {
  if (us <= 0) return 0;
  int b = 64 - __builtin_clzll(uint64_t(us));
  return b > 63 ? 63 : b;
}

constexpr uint8_t kFeVersion = 1;

// kind codes (closed enum, order is schema): get / put / append.
// Codes 3-6 (txn_prepare/txn_commit/txn_abort/txn_coord — the
// caps-gated txn extension, ISSUE 13, rpc/wire.py) are DELIBERATELY
// above kNumKinds: the C++ ingest path serves the columnar kvpaxos
// seam, which cannot run 2PC, so an ingest server never advertises
// `fe_txn` and this decoder rejects a stray txn frame as malformed
// (counted, connection-scoped) instead of mis-parsing it.  txn frames
// are decoded by the PYTHON side only (shardkv frontends keep the
// Python decode path).
constexpr int32_t kKindGet = 0;
constexpr int32_t kKindPut = 1;
constexpr int32_t kKindAppend = 2;
constexpr int32_t kNumKinds = 3;
constexpr int32_t kKindTxnPrepare = 3;  // Python-decode-only from here
constexpr int32_t kKindTxnCommit = 4;
constexpr int32_t kKindTxnAbort = 5;
constexpr int32_t kKindTxnCoord = 6;

// err codes: OK / ErrNoKey / ErrWrongGroup; 255 = pickled escape hatch
// (only the Python encoder emits it).
constexpr uint8_t kErrOther = 255;

constexpr size_t kHdrSize = 8;       // magic4 + flags u16 + nops u16
constexpr size_t kTcSize = 16;       // trace_id u64 + span_id u64
constexpr size_t kOpFixed = 23;      // kind u8 + cid u64 + cseq i64 +
                                     // klen u16 + vlen u32
constexpr uint16_t kFlagTrace = 1;
// Caps-gated v1 extensions (ISSUE 12, netfault — mirrored from
// rpc/wire.py): u32 op-budget ms / u32 frame crc32 follow the trace
// context, in flag-bit order.  Only clerks that saw the matching
// fe_caps advertisement send them, so a flag-less frame stays
// byte-identical to the original v1 layout.
constexpr uint16_t kFlagDeadline = 2;
constexpr uint16_t kFlagCrc = 4;

// crc32 (IEEE / zlib polynomial, bitwise-reflected) — matches Python's
// zlib.crc32 so the two decoders verify the same stamp.  The table is
// a C++11 magic static (thread-safe one-time init — the epoll loop and
// Python reply threads both compute CRCs).
struct Crc32Table {
  uint32_t t[256];
  Crc32Table() {
    for (uint32_t i = 0; i < 256; i++) {
      uint32_t c = i;
      for (int k = 0; k < 8; k++)
        c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
      t[i] = c;
    }
  }
};

inline uint32_t crc32(const uint8_t* p, size_t n, uint32_t seed = 0) {
  static const Crc32Table table;
  uint32_t c = seed ^ 0xFFFFFFFFu;
  for (size_t i = 0; i < n; i++)
    c = table.t[(c ^ p[i]) & 0xFF] ^ (c >> 8);
  return c ^ 0xFFFFFFFFu;
}

inline bool is_batch(const uint8_t* p, size_t n) {
  return n >= kHdrSize && p[0] == 'F' && p[1] == 'E' && p[2] == 'B';
}

template <typename T>
inline T load(const uint8_t* p) {
  T v;
  memcpy(&v, p, sizeof(T));
  return v;
}

template <typename T>
inline void store(uint8_t* p, T v) {
  memcpy(p, &v, sizeof(T));
}

}  // namespace fewire
