"""Shared build-and-load helper for the native C++ runtime components.

Each component is a single .cpp with a C ABI, compiled on first import into
`<repo>/build/` and loaded with ctypes; compile-to-temp + atomic rename keeps
concurrent processes from ever dlopening a half-written library.  Returns
None when no toolchain is available so callers can fall back to Python.

Provenance (ISSUE 11 satellite): staleness is decided by a CONTENT hash of
the source closure (the .cpp plus every repo-local ``#include "..."``
header, plus the compile command), not by mtimes — git checkouts reset
mtimes, which used to let a checked-in ``build/*.so`` silently shadow an
edited .cpp.  Each build writes a ``<so>.src.sha256`` sidecar; the tier-1
provenance test recomputes the hash and fails when the checked-in artifact
drifts from source.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import re
import subprocess
import threading

_HERE = os.path.dirname(os.path.abspath(__file__))
BUILD_DIR = os.path.join(os.path.dirname(os.path.dirname(_HERE)), "build")

CXX = ["g++", "-O2", "-std=c++17", "-shared", "-fPIC"]

# Sanitized build variants (ISSUE 19): a parallel artifact per variant,
# same C ABI, never the default load — the nemesis soak opts in via
# `sanitize="thread"` / TPU6824_NATIVE_SANITIZE=thread.  -O1 -g keeps
# TSAN's shadow instrumentation honest (O2 elides the racy loads TSAN
# exists to see) and the reports symbolized.
SANITIZE_CXX = {
    "thread": ["g++", "-O1", "-g", "-std=c++17", "-shared", "-fPIC",
               "-fsanitize=thread"],
}

_cache: dict[str, "ctypes.CDLL | None"] = {}
_lock = threading.Lock()

_INCLUDE_RE = re.compile(r'^\s*#\s*include\s+"([^"]+)"', re.MULTILINE)


def source_closure(src: str) -> list[str]:
    """The .cpp plus every repo-local quoted include, transitively —
    the file set whose content defines the artifact."""
    seen: list[str] = []
    todo = [os.path.abspath(src)]
    while todo:
        path = todo.pop()
        if path in seen or not os.path.exists(path):
            continue
        seen.append(path)
        with open(path, "r", encoding="utf-8", errors="replace") as f:
            text = f.read()
        base = os.path.dirname(path)
        for inc in _INCLUDE_RE.findall(text):
            todo.append(os.path.normpath(os.path.join(base, inc)))
    return sorted(seen)


def sanitized_name(so_name: str, sanitize: str) -> str:
    """`rpcserver.so` -> `rpcserver.tsan.so` (thread variant): the
    sanitized artifact lives NEXT TO the production one, never shadowing
    it."""
    tag = {"thread": "tsan"}[sanitize]
    stem, ext = os.path.splitext(so_name)
    return f"{stem}.{tag}{ext}"


def source_hash(src: str, cmd: "list[str] | None" = None) -> str:
    """sha256 over the compile command + the source closure's contents."""
    h = hashlib.sha256()
    h.update(" ".join(cmd or CXX).encode())
    for path in source_closure(src):
        h.update(b"\x00" + os.path.basename(path).encode() + b"\x00")
        with open(path, "rb") as f:
            h.update(f.read())
    return h.hexdigest()


def sidecar_path(so: str) -> str:
    return so + ".src.sha256"


def load(so_name: str, src: str,
         sanitize: "str | None" = None) -> "ctypes.CDLL | None":
    """Compile `src` (if its source closure's hash drifted) to
    BUILD_DIR/so_name and dlopen it.  `sanitize` selects an
    instrumented variant (see SANITIZE_CXX) built as a parallel
    artifact with its own sidecar — the variant's compile command is
    part of its content hash, so production and sanitized builds never
    satisfy each other's staleness check."""
    cmd = CXX if sanitize is None else SANITIZE_CXX[sanitize]
    if sanitize is not None:
        so_name = sanitized_name(so_name, sanitize)
    with _lock:
        if so_name in _cache:
            return _cache[so_name]
        so = os.path.join(BUILD_DIR, so_name)
        try:
            want = source_hash(src, cmd)
            have = None
            try:
                with open(sidecar_path(so)) as f:
                    have = f.read().strip()
            except OSError:
                pass
            if (not os.path.exists(so)) or have != want:
                os.makedirs(BUILD_DIR, exist_ok=True)
                tmp = f"{so}.{os.getpid()}.tmp"
                try:
                    subprocess.run(
                        cmd + ["-o", tmp, src],
                        check=True, capture_output=True,
                    )
                    os.replace(tmp, so)
                    # tpusan: ok(durable-write-discipline) — build-cache
                    # sidecar, not durable state: worst case after a crash
                    # is a spurious rebuild; durafs would drag the obs
                    # stack into this pre-import bootstrap path.
                    with open(sidecar_path(so) + f".{os.getpid()}.tmp",
                              "w") as f:
                        f.write(want + "\n")
                    os.replace(sidecar_path(so) + f".{os.getpid()}.tmp",
                               sidecar_path(so))
                finally:
                    if os.path.exists(tmp):
                        os.unlink(tmp)
            lib = ctypes.CDLL(so)
        except (OSError, subprocess.CalledProcessError):
            lib = None  # toolchain unavailable → caller's python fallback
        _cache[so_name] = lib
        return lib


# The artifact inventory (so → source), shared with the provenance test.
COMPONENTS = {
    "libintern6824.so": os.path.join(_HERE, "intern.cpp"),
    "liblru6824.so": os.path.join(_HERE, "lru.cpp"),
    "rpcserver.so": os.path.join(_HERE, "rpcserver.cpp"),
}
