"""Shared build-and-load helper for the native C++ runtime components.

Each component is a single .cpp with a C ABI, compiled on first import into
`<repo>/build/` and loaded with ctypes; compile-to-temp + atomic rename keeps
concurrent processes from ever dlopening a half-written library.  Returns
None when no toolchain is available so callers can fall back to Python.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_HERE = os.path.dirname(os.path.abspath(__file__))
BUILD_DIR = os.path.join(os.path.dirname(os.path.dirname(_HERE)), "build")

_cache: dict[str, "ctypes.CDLL | None"] = {}
_lock = threading.Lock()


def load(so_name: str, src: str) -> "ctypes.CDLL | None":
    """Compile `src` (if stale) to BUILD_DIR/so_name and dlopen it."""
    with _lock:
        if so_name in _cache:
            return _cache[so_name]
        so = os.path.join(BUILD_DIR, so_name)
        try:
            if (not os.path.exists(so)) or (
                os.path.getmtime(so) < os.path.getmtime(src)
            ):
                os.makedirs(BUILD_DIR, exist_ok=True)
                tmp = f"{so}.{os.getpid()}.tmp"
                try:
                    subprocess.run(
                        ["g++", "-O2", "-std=c++17", "-shared", "-fPIC",
                         "-o", tmp, src],
                        check=True, capture_output=True,
                    )
                    os.replace(tmp, so)
                finally:
                    if os.path.exists(tmp):
                        os.unlink(tmp)
            lib = ctypes.CDLL(so)
        except (OSError, subprocess.CalledProcessError):
            lib = None  # toolchain unavailable → caller's python fallback
        _cache[so_name] = lib
        return lib
