"""tpu6824 — a TPU-native distributed-systems framework.

A ground-up rebuild of the capabilities of the MIT 6.824 (Spring 2015) lab
stack — multi-instance Paxos, replicated key/value stores, a sharding
configuration service, a reconfiguring sharded KV store, primary/backup
replication with a view service, MapReduce, and persistent sharded storage —
re-architected for TPU hardware.

Instead of goroutines exchanging RPCs over Unix sockets (reference:
`paxos/rpc.go:24-42` and per-package `call()`), consensus state lives in dense
`(ngroups, ninstances, npeers)` device arrays advanced by one deterministic,
globally-stepped JAX kernel.  The asynchronous lossy network of the reference
becomes per-step boolean delivery-mask tensors; majority quorums become integer
reductions over the peer axis (a `psum` over ICI when the peer axis is sharded
across a device mesh).

Layout:
  core/      — the Paxos cell state machine kernel + host fabric + peer API
  services/  — kvpaxos, shardmaster, shardkv, viewservice, pbservice,
               lockservice, mapreduce, diskv
  parallel/  — mesh construction, sharding specs, shard_map'd kernel variants
  ops/       — hashing (fnv32a/key2shard), rebalance kernel, pallas kernels
  utils/     — config, errors, timing helpers
"""

__version__ = "0.1.0"

# Lazy top-level exports (PEP 562): `from tpu6824 import PaxosFabric`
# still works, but importing the bare package no longer drags in JAX —
# which keeps the tpusan CLI (`python -m tpu6824.analysis`, a pure-AST
# stdlib pass) and other JAX-free tooling paths fast and light.
_EXPORTS = {
    "PaxosFabric": "tpu6824.core.fabric",
    "Fate": "tpu6824.core.peer",
    "PaxosPeer": "tpu6824.core.peer",
    "make_group": "tpu6824.core.peer",
}


def __getattr__(name: str):
    mod = _EXPORTS.get(name)
    if mod is None:
        raise AttributeError(f"module 'tpu6824' has no attribute {name!r}")
    import importlib

    val = getattr(importlib.import_module(mod), name)
    globals()[name] = val  # cache: next access skips __getattr__
    return val


def __dir__():
    return sorted(set(globals()) | set(_EXPORTS))
