from tpu6824.ops.hashing import ihash, key2shard, ihash_batch, key2shard_batch  # noqa: F401
