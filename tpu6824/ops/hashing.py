"""Partitioning hash functions, bit-for-bit compatible with the reference.

- `ihash` is FNV-1a 32-bit, used by MapReduce to route a key to a reduce
  bucket (`mapreduce/mapreduce.go:185-189`, applied `:222`).
- `key2shard` routes a key to one of NShards shards by its first byte
  (`shardkv/client.go:75-82`).

Both are provided as scalar host functions and as vectorized JAX ops so a
batched mapper/partitioner can run the routing for an entire batch of keys on
device in one shot.
"""

import numpy as np

import jax.numpy as jnp

FNV_OFFSET32 = np.uint32(2166136261)
FNV_PRIME32 = np.uint32(16777619)

NSHARDS = 10  # shardmaster/common.go:35


def ihash(key: str) -> int:
    """FNV-1a 32-bit of the UTF-8 bytes of `key` (mapreduce/mapreduce.go:185-189)."""
    h = int(FNV_OFFSET32)
    for b in key.encode("utf-8"):
        h = ((h ^ b) * int(FNV_PRIME32)) & 0xFFFFFFFF
    return h


def key2shard(key: str, nshards: int = NSHARDS) -> int:
    """First byte of key mod nshards (shardkv/client.go:75-82); empty key -> 0."""
    if key:
        return key.encode("utf-8")[0] % nshards
    return 0


def ihash_batch(keys_u8: jnp.ndarray, lengths: jnp.ndarray) -> jnp.ndarray:
    """Vectorized FNV-1a over a padded byte matrix.

    keys_u8: (B, L) uint8, zero-padded rows.
    lengths: (B,) int32 actual byte lengths.
    Returns (B,) uint32 hashes identical to `ihash` per row.

    Implemented as a scan over the padded length so XLA compiles one fused
    loop; masked positions leave the accumulator unchanged.
    """
    B, L = keys_u8.shape
    pos = jnp.arange(L, dtype=jnp.int32)
    mask = pos[None, :] < lengths[:, None]  # (B, L)

    def body(h, i):
        b = keys_u8[:, i].astype(jnp.uint32)
        m = mask[:, i]
        h2 = (h ^ b) * jnp.uint32(FNV_PRIME32)
        return jnp.where(m, h2, h), None

    h0 = jnp.full((B,), FNV_OFFSET32, dtype=jnp.uint32)
    import jax

    h, _ = jax.lax.scan(body, h0, jnp.arange(L, dtype=jnp.int32))
    return h


def key2shard_batch(first_bytes: jnp.ndarray, nshards: int = NSHARDS) -> jnp.ndarray:
    """Vectorized key2shard: (B,) uint8 first bytes -> (B,) int32 shard ids."""
    return (first_bytes.astype(jnp.int32)) % nshards


def partition_keys(keys: list[str], nreduce: int) -> np.ndarray:
    """Route a batch of string keys to reduce buckets: ihash(key) % nreduce
    (mapreduce/mapreduce.go:222) computed for the whole batch in one device
    call.  Returns (B,) int64 bucket ids, bit-identical to the scalar path."""
    if not keys:
        return np.zeros((0,), np.int64)
    raw = [k.encode("utf-8") for k in keys]
    L = max(1, max(len(b) for b in raw))
    mat = np.zeros((len(raw), L), np.uint8)
    lengths = np.zeros((len(raw),), np.int32)
    for i, b in enumerate(raw):
        mat[i, : len(b)] = np.frombuffer(b, np.uint8)
        lengths[i] = len(b)
    h = np.asarray(ihash_batch(jnp.asarray(mat), jnp.asarray(lengths)))
    return (h.astype(np.int64)) % nreduce
