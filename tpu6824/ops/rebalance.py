"""Shard rebalancing as a fixed-point argmax/argmin kernel.

Capability parity: the shardmaster's rebalance step
(`shardmaster/server.go:195-226`) — move shards from the most-loaded group to
the least-loaded until the spread is ≤ 1, touching as few shards as possible.

Two implementations with identical semantics:
  - `rebalance_host`: the deterministic host algorithm the replicated state
    machine applies (must be bit-identical across replicas, so all ties break
    toward the lowest group id);
  - `rebalance_jax`: the same fixed point as a `lax.while_loop` over the
    shard→group assignment vector, jittable and vmappable over many
    independent configurations at once (the batched-groups axis of the
    north star).
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from tpu6824.ops.hashing import NSHARDS

UNASSIGNED = 0  # gid 0 = invalid/unassigned (shardmaster/common.go Config zero value)


def rebalance_host(shards: list[int], gids: list[int]) -> list[int]:
    """Rebalance `shards` (shard index → gid) over active `gids`.

    Rules, in order:
      1. no active groups → all shards UNASSIGNED;
      2. shards on dead/unknown groups (incl. UNASSIGNED) go to the currently
         least-loaded group;
      3. while spread > 1, move one shard from the most-loaded to the
         least-loaded group.  Ties break to the lowest gid; within a group
         the lowest-numbered shard moves first.  Deterministic, so every
         replica computes the same config.
    """
    shards = list(shards)
    if not gids:
        return [UNASSIGNED] * len(shards)
    order = sorted(gids)

    def counts():
        return {g: sum(1 for s in shards if s == g) for g in order}

    def argmin_g():
        c = counts()
        return min(order, key=lambda g: (c[g], g))

    def argmax_g():
        c = counts()
        return max(order, key=lambda g: (c[g], -g))

    for i, g in enumerate(shards):
        if g not in order:
            shards[i] = argmin_g()

    while True:
        c = counts()
        hi, lo = argmax_g(), argmin_g()
        if c[hi] - c[lo] <= 1:
            return shards
        i = next(i for i, g in enumerate(shards) if g == hi)
        shards[i] = lo


def rebalance_jax(shards: jnp.ndarray, active: jnp.ndarray) -> jnp.ndarray:
    """JAX twin of `rebalance_host`.

    shards: (NSHARDS,) int32 of gids.
    active: (K,) bool over a static gid universe [1..K] — active[g-1] says gid
            g is a live group.
    Returns (NSHARDS,) int32.  Jit/vmap-friendly: fixed trip bounds, no
    data-dependent shapes.
    """
    K = active.shape[0]
    gid_univ = jnp.arange(1, K + 1, dtype=jnp.int32)
    BIG = jnp.int32(NSHARDS + 1)
    any_active = active.any()

    def counts(sh):
        return (sh[None, :] == gid_univ[:, None]).sum(-1).astype(jnp.int32)

    def argmin_gid(sh):
        c = jnp.where(active, counts(sh), BIG)
        return gid_univ[jnp.argmin(c)]  # ties → lowest gid (argmin first index)

    def orphan_body(i, sh):
        bad = ~(active & (gid_univ == sh[i])).any()
        return sh.at[i].set(jnp.where(bad, argmin_gid(sh), sh[i]))

    sh = jax.lax.fori_loop(0, NSHARDS, orphan_body, shards.astype(jnp.int32))

    def cond(sh):
        c = jnp.where(active, counts(sh), BIG)
        cmax = jnp.where(active, counts(sh), -1).max()
        return any_active & (cmax - c.min() > 1)

    def body(sh):
        c = counts(sh)
        lo = gid_univ[jnp.argmin(jnp.where(active, c, BIG))]
        # argmax with lowest-gid tie-break: take first index of max.
        hi = gid_univ[jnp.argmax(jnp.where(active, c, -1))]
        # lowest-numbered shard of hi moves:
        idx = jnp.argmax(sh == hi)
        return sh.at[idx].set(lo)

    sh = jax.lax.while_loop(cond, body, sh)
    return jnp.where(any_active, sh, jnp.full_like(sh, UNASSIGNED))
