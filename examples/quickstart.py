"""tpu6824 in 60 seconds — the batched consensus runtime end to end.

    JAX_PLATFORMS=cpu PYTHONPATH=.. python quickstart.py   (or on TPU: as-is)

Walks the three layers a reference (Go labs) user needs:
  1. raw Paxos over the fabric (Make/Start/Status/Done/Min/Max),
  2. a linearizable KV service (kvpaxos) on the same fabric,
  3. the sharded capstone (shardmaster + shardkv) with a live Join.
"""

import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                os.pardir))
if os.environ.get("JAX_PLATFORMS"):
    import jax

    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

from tpu6824.core.fabric import PaxosFabric
from tpu6824.core.peer import Fate, make_group


def wait(pred, timeout=20.0):
    t0 = time.time()
    while time.time() - t0 < timeout:
        if pred():
            return True
        time.sleep(0.01)
    return False


# --- 1. Raw Paxos: 4 independent 3-peer groups on one device fabric -------
fab = PaxosFabric(ngroups=4, npeers=3, ninstances=32, auto_step=True)
peers = make_group(fab, 0)                  # paxos.Make analog, group 0
peers[0].start(0, "hello consensus")        # paxos.Start (async)
fab.start_many([(g, 0, 0, g * 100) for g in (1, 2)])   # batched API
assert wait(lambda: peers[2].status(0)[0] == Fate.DECIDED)
print("group 0 decided:", peers[2].status(0))
print("groups 1-2     :", fab.status_many([(g, 1, 0) for g in (1, 2)]))
for p in peers:
    p.done(0)                               # Done/Min window GC

# --- 2. kvpaxos: a linearizable replicated KV on the same fabric ----------
from tpu6824.services.kvpaxos import Clerk, KVPaxosServer

kv_servers = [KVPaxosServer(fab, 3, p) for p in range(3)]  # group 3 lanes
ck = Clerk(kv_servers)
ck.put("lang", "jax")
ck.append("lang", "+pallas")
print("kvpaxos get    :", ck.get("lang"))
assert ck.get("lang") == "jax+pallas"

# --- 3. Sharded capstone: shardmaster + shardkv groups, live Join ---------
from tpu6824.services.shardkv import ShardSystem

sysk = ShardSystem(ngroups=2, nreplicas=3, ninstances=32)
try:
    g0, g1 = sysk.gids
    sysk.join(g0)
    sck = sysk.clerk()
    sck.put("a", "alpha", timeout=30.0)
    sysk.join(g1)                            # shards rebalance live
    sck.append("a", "!", timeout=30.0)
    print("shardkv get    :", sck.get("a", timeout=30.0))
    assert sck.get("a", timeout=30.0) == "alpha!"
    cfg = sysk.sm_clerk().query(-1)
    print("shard map      :", dict(enumerate(cfg.shards)))
finally:
    sysk.shutdown()

for s in kv_servers:
    s.dead = True
fab.stop_clock()
print("OK — three layers, one fabric.")
